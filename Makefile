# Test / bench matrix (the role of the reference's Makefile:28-51, which ran
# every pytest file under `mpirun -np 4`; here the fast tier runs on the
# virtual 8-device CPU mesh in-process and the slow tier adds the real
# multi-process `bfrun` launches).

PYTEST = python -m pytest -q

.PHONY: test test-fast test-slow test-all test-onchip bench bench-comm \
        bench-comm-smoke native telemetry-smoke prof-smoke transport-smoke \
        stripe-smoke tracerec-smoke async-smoke ffi-smoke fused-smoke \
        probe-smoke placement-smoke synth-smoke hier-smoke sharded-smoke \
        chaos-smoke chaos links-smoke tune-smoke metrics-lint

# Fast gate: ~3 min on the CPU mesh (in-process virtual-mesh tests only;
# grew a few oracle tests in round 4); run on every change, plus the
# schedule-regression smoke (bench_comm asserts the min-round repack is
# output-equivalent and never worse than naive — a broken repack fails
# here loudly, not as a silent slowdown).  `native` runs first so the
# window-transport hot path is fresh (graceful skip without a toolchain —
# every native consumer has a Python fallback).
test: native test-fast bench-comm-smoke prof-smoke transport-smoke \
      stripe-smoke tracerec-smoke async-smoke ffi-smoke fused-smoke \
      probe-smoke placement-smoke synth-smoke hier-smoke sharded-smoke \
      chaos-smoke links-smoke tune-smoke metrics-lint
test-fast:
	$(PYTEST) tests/ -m "not slow"

# Slow tier: multi-process bfrun launches, example e2e runs, heavy model
# grids, on-chip kernel checks (TPU tests self-skip without a chip).
test-slow:
	$(PYTEST) tests/ -m "slow"

test-all:
	$(PYTEST) tests/

# On-chip subset only (flash/mosaic kernels compiled for the real TPU).
test-onchip:
	$(PYTEST) tests/ -m "slow" -k "on_tpu"

bench:
	python bench.py

# Gossip hot-path microbench: rounds/edges/walltime, naive shift-distance
# schedule vs the min-round repack (ops/schedule_opt.py), CPU-runnable.
bench-comm:
	python bench_comm.py

# Tiny-mesh CI smoke of the same: fails loudly on any schedule regression
# (more rounds than naive, off the König bound, or output drift > 1e-6).
bench-comm-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --smoke

# End-to-end telemetry check: start the /metrics endpoint, drive one
# collective, scrape /metrics + /healthz and assert the core series exist.
telemetry-smoke:
	env JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	    python -m bluefog_tpu.utils.telemetry

# End-to-end profiler check: tiny CPU-backed profiled loop — asserts the
# bf_step_phase_seconds histogram appears in /metrics, the straggler
# report in /healthz, and that trace-merge emits valid JSON with one
# process lane per rank.
prof-smoke:
	env JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	    python -m bluefog_tpu.utils.profiler

# Physical-placement CI gate: modeled link-load report on simulated 4x8
# and 8x8 tori (asserts the optimizer+packer cut random-regular max-link-
# load >= 2x and never worsen shift-structured placements) plus an end-to-
# end check that the placement permutation is BIT-identical to enumeration
# order on the virtual CPU mesh.
placement-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --placement-smoke

# Schedule-synthesis CI gate: modeled serial-link-time report across
# ring/Exp2/star/random-regular on simulated 4x8, 8x8 and multi-slice
# tori — asserts the sketch synthesis strictly beats the congestion
# repack on the acceptance cases (and ties ONLY at the provable
# busiest-link-total lower bound), preserves the effective weight matrix
# bit-identically, stays within the round budget, drives a synthesized
# schedule end-to-end on the virtual CPU mesh (<= 1e-6), and that
# BLUEFOG_TPU_SCHEDULE_SYNTH=0 restores the PR-5 dispatch path.
synth-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --synth-smoke

# Hierarchical-gossip CI gate: on simulated 2x(4x8) and 4x(4x4) multi-
# slice tori the two-level mode (dense ICI inner exp2, sparse one-peer
# DCN outer at cadence 2 with sparse:0.5 compression) must cut per-step
# DCN wire rows AND modeled inter-slice serial link time >= 4x vs flat
# exp2 at equal-or-better simulated consensus distance; plus the e2e
# product-topology equivalence (<= 1e-6), the BLUEFOG_TPU_HIER=0
# bit-identity check, and the sparse:<frac> OP_BATCH round-trip.
hier-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --hier-smoke

# Sharded-gossip CI gate: the ShardPlan byte model must scale per-step
# DCN bytes with the replicated fraction ONLY (25/50/75% MoE trees on a
# simulated 16-rank, 4-group mesh; per-group schedules never emit a
# cross-group edge), and the 8-device executor leg must match the dense
# replicated oracle and the per-group sharded oracle <= 1e-6, bill
# exactly rep_row_bytes x dcn_edges x steps to {level="dcn"} with NO
# sharded byte on the DCN, and be BIT-identical to the no-spec path
# under BLUEFOG_TPU_SHARDED_GOSSIP=0 or a fully replicated tree.
sharded-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --sharded-smoke

# CPU-runnable loopback two-transport exchange over the coalesced DCN
# path, run twice: native hot path allowed (asserts the C++ batch/drain/
# fold path actually ENGAGED when available, batched delivery happened,
# and the batch + bf_win_native_* telemetry series exist) and pinned to
# the Python fallback (BLUEFOG_TPU_WIN_NATIVE=0 must restore the PR-4
# path exactly).  No timing assertion — `python bench_comm.py --transport`
# full runs gate the >= 5x small-row messages/s win of the native path.
transport-smoke:
	python bench_comm.py --transport-smoke
	env BLUEFOG_TPU_WIN_NATIVE=0 python bench_comm.py --transport-smoke

# Multi-stream striped transport CI gate: asserts >= 2 stripes engage on
# the loopback rig (independent sockets/workers/arenas per peer, frames
# sharded by (window, row)) with the per-stripe telemetry series present
# (bf_win_tx_stripe_bytes_total, (peer, stripe)-labeled queue-depth
# gauges, the decode-pool busy gauge), and that a pinned
# BLUEFOG_TPU_WIN_STRIPES=1 leg reproduces the pre-stripe wire exactly
# (one sender, send-order delivery, fence weight 0.0).  No timing
# assertion; `python bench_comm.py --transport` full runs carry the
# 1/2/4-stripe x row-size x concurrent-peers sweep.
stripe-smoke:
	python bench_comm.py --stripe-smoke
	env BLUEFOG_TPU_WIN_NATIVE=0 python bench_comm.py --stripe-smoke

# Message-level tracing CI gate: flight recorder armed + wire trace tags
# sampled at 1/2 through a loopback window-store pair — asserts the
# per-edge contribution-age histograms/gauges land on /metrics and in
# /healthz, the recorder dump decodes into a valid merged chrome trace
# with matched cross-rank flow arrows (trace-gossip), and that a
# BLUEFOG_TPU_TELEMETRY=0 leg leaves the registry completely untouched.
# With BLUEFOG_TPU_TRACE_SAMPLE unset and the recorder off, nothing in
# this PR runs at all — the wire stays bitwise identical (unit-tested).
tracerec-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --tracerec-smoke

# Barrier-free async gossip CI gate: a loopback two-transport rig with
# BLUEFOG_TPU_ASYNC=1 and the sender's origin-step clock pinned behind
# the receiver's (the injected delay) — asserts the bounded-staleness
# fold rejects the over-age accumulates into the stale-residual store
# (bf_win_stale_rejected_total on /metrics, the "async" block in
# /healthz), that win_fold_stale_residuals restores the held mass into
# staging EXACTLY (push-sum conservation on real wire frames), and that
# a BLUEFOG_TPU_TELEMETRY=0 leg leaves the registry untouched.  Run on
# the native hot path AND pinned to the Python fallback.
async-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --async-smoke
	env JAX_PLATFORMS=cpu BLUEFOG_TPU_WIN_NATIVE=0 \
	    python bench_comm.py --async-smoke

# Zero-copy XLA put-path CI gate: loopback window-store puts of DEVICE
# arrays through the BLUEFOG_TPU_WIN_XLA plan dispatch — asserts the FFI
# path engaged and bf_win_host_copy_bytes_total reports ZERO put-side
# staging bytes for dense f32 rows.  Graceful skip (not a failure) when
# jax.ffi, the bf_xla native symbols, or the toolchain are absent — the
# documented degraded mode.  No timing assertion here;
# `python bench_comm.py --ffi` full runs gate the >= 2x dispatch-overhead
# win over the PR-9 native put path for rows >= 4 KiB.
ffi-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --ffi-smoke

# Whole-step compilation CI gate (BLUEFOG_TPU_FUSED_STEP): the gossip
# training step lowered into one XLA program with per-bucket FFI puts
# issued by data dependence.  Structural assertions on the loopback
# transport rig, no timing: every step takes the fused path
# (bf_fused_step_active = 1, in-program puts counted), the fused
# trajectory is bitwise identical to the eager oracle over the same
# gradient stream, BLUEFOG_TPU_FUSED_STEP=0 builds nothing and registers
# nothing, and a fused=True optimizer without the native XLA put handler
# falls back to eager with exactly one warning.  Graceful skip when the
# native bf_xla_win_put_pass symbols are absent.  The >= 1.5x end-to-end
# step-time win is gated by `python bench_comm.py --fused` full runs.
fused-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --fused-smoke

# In-program probe CI gate (BLUEFOG_TPU_PROBE, utils/probes.py): run the
# fused loopback rig with probes on and assert the whole reconcile loop —
# every step served fused, a measured bf_fused_overlap_ratio in (0, 1],
# probe events drained (bf_probe_events_total > 0), one
# bf_fused_bucket_issue_seconds series per fusion bucket, a finite
# measured-vs-modeled divergence, and trace-merge'd timeline output
# carrying the fused-probe lanes.  Graceful skip when the native core
# lacks the bf_probe_* / bf_xla_probe symbols (the feature then degrades
# to the labeled-but-unattributed fused-step phase, tested in tier 1).
probe-smoke:
	env JAX_PLATFORMS=cpu python bench_comm.py --probe-smoke

# Churn-controller CI gate: a real 4-process `bfrun --chaos` gang on the
# CPU backend, one rank SIGKILLed mid-gossip — asserts the survivors reach
# failure consensus (a committed membership epoch in /healthz), re-plan
# onto a survivor topology without a global restart within a bounded
# number of steps, converge to the survivor-consensus optimum, and keep
# post-recovery step time within 1.5x the pre-failure median.  The
# delay leg runs the same gang under a `delay:` fault in BOTH gossip
# modes: synchronous survivors must DEGRADE toward the slowest rank's
# cadence while BLUEFOG_TPU_ASYNC=1 survivors hold the no-fault step
# time, the merely-slow rank is NOT evicted even with step-lag eviction
# armed (the widened async bound), and both modes reach the same
# consensus optimum (matched final loss through rejection + backstop).
# The JOIN leg (elastic scale-up, ops/gang.py) runs a coordinator-free
# `bfrun --elastic` gang, kills rank 2 mid-training, admits a fresh
# `bfrun --join` process through the persisted endpoint directory and
# asserts exactly one committed grow epoch + convergence to the
# FULL-gang optimum; the KILL-RANK-0 leg kills rank 0 instead — the
# gang must survive (membership/bootstrap never touch a coordinator)
# and admit a replacement for rank 0 the same way.
chaos-smoke:
	env JAX_PLATFORMS=cpu python -m bluefog_tpu.tools chaos --smoke
	env JAX_PLATFORMS=cpu python -m bluefog_tpu.tools chaos --delay-smoke
	env JAX_PLATFORMS=cpu python -m bluefog_tpu.tools chaos --join-smoke
	env JAX_PLATFORMS=cpu python -m bluefog_tpu.tools chaos --kill0-smoke

# Link-observatory CI gate: a real 4-process `bfrun --chaos` gang on the
# CPU backend with a `linkdelay:` fault holding one rank's outbound DATA
# links at +60ms — asserts the online estimator's per-edge delay EWMAs
# converge on the injected delay on the affected edges while unaffected
# edges stay flat, measured-vs-modeled divergence crosses the alert
# threshold, exactly the matching BLUEFOG_TPU_SLO rule fires on the
# receiver ranks (bf_slo_breaches_total + degraded /healthz links block
# + one flight-recorder dump) while a co-armed quiet rule stays silent,
# every rank computes the IDENTICAL merged link matrix
# (bf.link_report() agreement), and `tools top` renders one complete
# frame against the live gang's /metrics endpoints.  The second leg
# pins BLUEFOG_TPU_LINK_OBS=0 through the transport smoke: the
# off-switch must be bitwise inert (not one bf_link_* series).
links-smoke:
	env JAX_PLATFORMS=cpu python -m bluefog_tpu.tools chaos --links-smoke
	env BLUEFOG_TPU_LINK_OBS=0 python bench_comm.py --transport-smoke

# Self-tuning control-plane smoke: the same 4-proc gang started on a
# full mesh (the wrong topology for the coming fault), run twice.  With
# BLUEFOG_TPU_TUNE=1 the tuner must measure the hot edges, commit
# EXACTLY ONE numbered adaptation epoch agreed by every rank (re-route
# + knob moves), recover >= 2x of the delayed rank's lost gossip
# throughput without a restart, and surface the epoch in the /healthz
# "tuner" block and the `tools top` tune column.  With
# BLUEFOG_TPU_TUNE=0 pinned, the identical fault must leave the send
# schedule bitwise unchanged and register zero bf_tune_* series — the
# default-off contract (both legs run inside the one driver).
tune-smoke:
	env JAX_PLATFORMS=cpu python -m bluefog_tpu.tools chaos --tune-smoke

# Metrics/doc drift gate: AST-scan every bf_* series the package
# registers against the docs/observability.md inventory, BOTH ways —
# fails on an undocumented metric or a stale inventory row.
metrics-lint:
	python -m bluefog_tpu.tools.metrics_lint

# Full interactive chaos demo (same harness, bigger run; see
# `python -m bluefog_tpu.tools chaos --help` for kill/delay/partition
# fault specs).
chaos:
	env JAX_PLATFORMS=cpu python -m bluefog_tpu.tools chaos

# Native core (+ the _bf_fastcall hot-path module when Python.h exists).
# Graceful skip with a clear log line when no C++ toolchain is present:
# every native consumer (schedule compile, timeline, window transport)
# carries a pure-Python fallback, so `make test` still runs — the
# transport smoke simply exercises the fallback path.
native:
	@if command -v $(CXX) >/dev/null 2>&1 || command -v g++ >/dev/null 2>&1; \
	then $(MAKE) -C bluefog_tpu/native; \
	else echo "make native: no C++ toolchain found (CXX=$(CXX)) — SKIPPING" \
	          "the native build; Python fallbacks stay in use"; fi
