"""Asynchronous one-sided optimizers: win_put / pull-get / push-sum.

Parity targets (reference ``torch/optimizers.py``):
  * ``_DistributedWinOptimizer`` (:844-1024) -> ``DistributedWinPutOptimizer``
    (push style) and ``DistributedPullGetOptimizer`` (pull style): named
    windows; each step pushes (or pulls) parameters along the topology's
    edges and combines via ``win_update``.
  * ``_DistributedPushSumOptimizer`` (:1026-1178) -> ``DistributedPushSumOptimizer``:
    column-stochastic ``win_accumulate`` of the parameters together with the
    push-sum weight scalar (the "associated-P" window, reference
    ``mpi_context.cc:136-156``), ``win_update_then_collect``, and de-bias
    division — converges to the network average on any strongly-connected
    digraph even though single steps are biased.

These run through the host-side window store (``bluefog_tpu.ops.window``) —
they are the *async gossip* family, deliberately outside jit: communication
overlaps compute via the store's worker pool, mirroring the reference's
nonblocking RMA + finalizer threads.  The local "adapt" math is still jitted
(vmapped over the rank axis).

Fusion: by default (``fuse=True``) the whole parameter pytree travels through
ONE window — each rank's leaves raveled into a single flat row — so a model
with hundreds of parameters issues one transport message per edge per step
instead of one per (leaf, edge).  This mirrors the collective family's
``ravel_pytree`` fusion (``optim/functional.py``) and the reference's fusion
buffer (``tensor_queue.h:70-92``); ``fuse=False`` keeps per-leaf windows (the
reference's per-parameter layout, ``torch/optimizers.py:933-944``).

Multi-process semantics: each process is authoritative for the ranks of its
local devices only.  ``step`` returns rank-major trees whose NON-owned rows
are frozen at their value from the previous step's input — they are never
silently installed from stale window copies (each process trains its own
ranks, exactly like the reference's one-tensor-per-process model).  Use
:meth:`gather` to materialize every rank's fresh parameters for evaluation.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bluefog_tpu import basics
from bluefog_tpu.ops import window as W
from bluefog_tpu.optim.functional import DistOptState

__all__ = [
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
]


def _leaf_names(tree, prefix: str):
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [f"{prefix}.{jax.tree_util.keystr(p)}" for p, _ in paths]


class _WindowOptimizerBase:
    """Shared plumbing: fused (or per-leaf) windows + vmapped local update."""

    def __init__(self, base: optax.GradientTransformation, *,
                 window_prefix: str, num_steps_per_communication: int = 1,
                 fuse: bool = True):
        self.base = base
        self.window_prefix = window_prefix
        self.num_steps_per_communication = int(num_steps_per_communication)
        self.fuse = bool(fuse)
        self._names: List[str] = None
        self._update_fn = None
        self._n = 0
        self._shapes = None   # per-leaf (n, *rest) shapes, fused mode
        self._dtypes = None   # per-leaf dtypes (concatenate promotes; cast back)
        self._splits = None   # np.cumsum of per-leaf flat sizes, fused mode

    # -- payload layout ----------------------------------------------------
    def _payloads(self, tree) -> List[np.ndarray]:
        """Rank-major arrays to ship, one per window (1 when fused)."""
        leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]
        if not self.fuse:
            return leaves
        return [np.concatenate([x.reshape(self._n, -1) for x in leaves],
                               axis=1)]

    def _rebuild(self, arrays: List, like):
        """Inverse of :meth:`_payloads` — back to the pytree structure."""
        treedef = jax.tree_util.tree_structure(like)
        if self.fuse:
            flat = np.asarray(arrays[0])
            parts = np.split(flat, self._splits[:-1], axis=1)
            # Cast back to each leaf's own dtype: the fused concatenate
            # promoted mixed-precision trees to a common wire dtype.
            leaves = [p.reshape(s).astype(d)
                      for p, s, d in zip(parts, self._shapes, self._dtypes)]
        else:
            leaves = arrays
        return jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(x) for x in leaves])

    def _merge_owned(self, prev, new):
        """Freeze non-owned rows (multi-process): rows of ranks owned by
        other processes keep their previous value instead of receiving
        stale window copies."""
        if W._store.distrib is None:
            return new
        mask = np.zeros(self._n, bool)
        mask[W._owned_ranks(self._n)] = True

        def one(p, q):
            m = jnp.asarray(mask.reshape((-1,) + (1,) * (jnp.ndim(q) - 1)))
            return jnp.where(m, q, p)
        return jax.tree.map(one, prev, new)

    def gather(self, params):
        """Materialize every rank's authoritative rows (for evaluation):
        allgathers owned rows across processes; identity single-process."""
        d = W._store.distrib
        if d is None:
            return params
        from jax.experimental import multihost_utils
        owner = np.array([d.rank_owner[r] for r in range(self._n)])
        rows = np.arange(self._n)

        def one(leaf):
            g = np.asarray(multihost_utils.process_allgather(
                np.asarray(leaf)))
            return jnp.asarray(g[owner, rows])
        return jax.tree.map(one, params)

    # -- lifecycle ---------------------------------------------------------
    def init(self, params) -> DistOptState:
        basics._require_init()
        self._n = basics.size()
        leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(params)]
        if self.fuse:
            self._shapes = [x.shape for x in leaves]
            self._dtypes = [x.dtype for x in leaves]
            sizes = [int(np.prod(s[1:])) for s in self._shapes]
            self._splits = np.cumsum(sizes)
            self._names = [f"{self.window_prefix}.fused"]
        else:
            self._names = _leaf_names(params, self.window_prefix)
        for name, payload in zip(self._names, self._payloads(params)):
            W.win_create(payload, name, zero_init=self._zero_init)
        base = self.base

        def init_one(p):
            return base.init(p)
        st = jax.jit(jax.vmap(init_one))(jax.tree.map(jnp.asarray, params))
        self._update_fn = jax.jit(jax.vmap(
            lambda g, s, p: base.update(g, s, p)))
        return DistOptState(st, jnp.asarray(0, jnp.int32))

    def _local_adapt(self, params, grads, state: DistOptState):
        updates, base_state = self._update_fn(grads, state.base, params)
        new_params = jax.tree.map(lambda p, u: p + u, params, updates)
        return new_params, base_state

    def free(self):
        for name in self._names or []:
            W.win_free(name)
        self._names = None

    def _quiesce(self) -> None:
        """Complete every in-flight window op this optimizer issued (and,
        multi-process, fence the transport) so a snapshot cannot miss
        queued or in-flight gossip mass."""
        if W._store.distrib is not None:
            W.win_fence()

    def _require_windows(self, what: str):
        if not self._names:
            raise RuntimeError(
                f"{type(self).__name__}.{what}: no windows exist — call "
                "init() first (and not after free()); a silent empty "
                "snapshot would lose all gossip state")
        return self._names

    def window_state_dict(self):
        """Snapshot every window this optimizer owns (checkpoint-ready
        numpy tree keyed by window name; pair with
        :meth:`load_window_state_dict` after re-``init`` on restart so
        in-staging gossip mass survives elastic restarts).  Quiesces
        in-flight ops first — overlapped puts and transport-in-flight
        mass land before the snapshot.

        Multi-process: COLLECTIVE — the quiesce fences the transport
        (``win_fence`` ends in a barrier), so every process must call
        this (and :meth:`load_window_state_dict`) together, like the
        reference's collective window ops."""
        names = self._require_windows("window_state_dict")
        self._quiesce()
        return {name: W.win_state_dict(name) for name in names}

    def load_window_state_dict(self, state) -> None:
        names = set(self._require_windows("load_window_state_dict"))
        self._quiesce()  # an in-flight put landing after the restore
        #                  would corrupt the just-restored state
        snap = dict(state)
        if set(snap) != names:
            raise ValueError(
                f"{type(self).__name__}.load_window_state_dict: snapshot "
                f"windows {sorted(snap)} do not match this optimizer's "
                f"{sorted(names)} — was the snapshot taken with a "
                "different fuse= setting or window_prefix?")
        for name, s in snap.items():
            W.win_load_state_dict(name, s)

    _zero_init = False


class DistributedWinPutOptimizer(_WindowOptimizerBase):
    """Push-style async optimizer: adapt locally, ``win_put`` the new
    parameters to out-neighbors, combine received neighbor state via
    ``win_update`` (reference factory ``torch/optimizers.py:1271``).

    ``step(..., dst_weights=...)`` takes the same weight forms as
    ``bf.win_put`` and is re-resolvable every call (dynamic topologies).

    ``overlap=True`` makes the put genuinely asynchronous: ``step`` issues
    the nonblocking put and returns WITHOUT waiting — the put executes on
    the worker pool while the caller computes the next forward/backward,
    and the next step's ``win_update`` combines whatever has arrived (one
    extra step of staleness, the reference's actual async operating mode:
    its win optimizers overlapped RMA with compute via hooks,
    ``torch/optimizers.py:889-909``).  The previous put is always waited
    before the next one is issued, so per-window ordering holds even with
    a multi-worker pool.

    Note that in overlap mode the rank's OWN row lags too, not just the
    neighbors': a put self-publishes the adapted parameters into the local
    window, so when step ``t+1``'s ``win_update`` runs before step ``t``'s
    put has landed, the combine is taken over step ``t-1``'s published
    self value — step ``t``'s local adapt result reaches the combined
    state one step late, same as its neighbors see it."""

    def __init__(self, base, *, window_prefix: str = "winput",
                 num_steps_per_communication: int = 1, fuse: bool = True,
                 overlap: bool = False):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication,
                         fuse=fuse)
        self.overlap = bool(overlap)
        self._pending: List[int] = []

    def step(self, params, grads, state: DistOptState, *,
             dst_weights=None, require_mutex: bool = True):
        new_params, base_state = self._local_adapt(params, grads, state)
        t = int(state.step)
        if (t + 1) % self.num_steps_per_communication == 0:
            # Ordering: the previous overlapped put must complete before a
            # new one targets the same window.
            self._drain_pending()
            payloads = self._payloads(new_params)
            handles = [
                W.win_put_nonblocking(payload, name,
                                      dst_weights=dst_weights,
                                      require_mutex=require_mutex)
                for name, payload in zip(self._names, payloads)]
            if self.overlap:
                self._pending = handles
            else:
                for h in handles:
                    W.win_wait(h)
            combined = [W.win_update(name, require_mutex=require_mutex)
                        for name in self._names]
            new_params = self._rebuild(combined, params)
        return (self._merge_owned(params, new_params),
                DistOptState(base_state, state.step + 1))

    def _drain_pending(self) -> None:
        for h in self._pending:   # overlapped puts must land first
            W.win_wait(h)
        self._pending = []

    def free(self):
        self._drain_pending()
        super().free()

    def _quiesce(self) -> None:
        self._drain_pending()
        super()._quiesce()


class DistributedPullGetOptimizer(_WindowOptimizerBase):
    """Pull-style async optimizer: adapt locally, publish self memory, then
    ``win_get`` neighbors' parameters and combine (reference factory
    ``torch/optimizers.py:1225``)."""

    def __init__(self, base, *, window_prefix: str = "pullget",
                 num_steps_per_communication: int = 1, fuse: bool = True):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication,
                         fuse=fuse)

    def step(self, params, grads, state: DistOptState, *,
             src_weights=None, require_mutex: bool = True):
        new_params, base_state = self._local_adapt(params, grads, state)
        t = int(state.step)
        if (t + 1) % self.num_steps_per_communication == 0:
            payloads = self._payloads(new_params)
            # Publish my new parameters as the window's exposed memory (the
            # dst_weights={} put touches no edges — it only refreshes main).
            publish = [W.win_put_nonblocking(payload, name,
                                             self_weight=1.0, dst_weights={})
                       for name, payload in zip(self._names, payloads)]
            for h in publish:
                W.win_wait(h)
            handles = [W.win_get_nonblocking(name, src_weights=src_weights,
                                             require_mutex=require_mutex)
                       for name in self._names]
            for h in handles:
                W.win_wait(h)
            combined = [W.win_update(name, require_mutex=require_mutex)
                        for name in self._names]
            new_params = self._rebuild(combined, params)
        return (self._merge_owned(params, new_params),
                DistOptState(base_state, state.step + 1))


class DistributedPushSumOptimizer(_WindowOptimizerBase):
    """Async push-sum gossip SGD (reference factory
    ``torch/optimizers.py:1180``).

    Every step: local adapt, column-stochastic ``win_accumulate`` of the raw
    parameters (each rank splits weight ``1/(outdeg+1)`` over itself and its
    out-neighbors), ``win_update_then_collect``, and the associated-P scalar
    tracks the accumulated weight so ``debias`` recovers unbiased iterates.
    Gradients should be evaluated at ``debias(params)``.
    """

    _zero_init = True

    def __init__(self, base, *, window_prefix: str = "pushsum",
                 num_steps_per_communication: int = 1, fuse: bool = True):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication,
                         fuse=fuse)

    def init(self, params) -> DistOptState:
        W.turn_on_win_ops_with_associated_p()
        return super().init(params)

    def _outgoing_weights(self) -> Dict[int, float]:
        topo = basics.load_topology()
        n = basics.size()
        from bluefog_tpu import topology as topology_util
        w = {}
        for r in range(n):
            outs = topology_util.out_neighbor_ranks(topo, r)
            share = 1.0 / (len(outs) + 1.0)
            for o in outs:
                w[(r, o)] = share
        return w

    def _self_share(self) -> np.ndarray:
        topo = basics.load_topology()
        n = basics.size()
        from bluefog_tpu import topology as topology_util
        return np.array([
            1.0 / (len(topology_util.out_neighbor_ranks(topo, r)) + 1.0)
            for r in range(n)])

    def step(self, params, grads, state: DistOptState, *,
             dst_weights=None, require_mutex: bool = True):
        new_params, base_state = self._local_adapt(params, grads, state)
        if dst_weights is None:
            dst_weights = self._outgoing_weights()
        self_share = self._self_share()
        collected = []
        for name, payload in zip(self._names, self._payloads(new_params)):
            # win_accumulate applies self_weight AFTER the edge sends, so the
            # out-edges carry w * p_old and per-source mass
            # (self_share + sum_out w == 1) is conserved — the push-sum
            # column-stochastic invariant.
            h = W.win_accumulate_nonblocking(
                payload, name, self_weight=self_share,
                dst_weights=dst_weights, require_mutex=require_mutex)
            W.win_wait(h)
            collected.append(W.win_update_then_collect(
                name, require_mutex=require_mutex))
        new_params = self._rebuild(collected, params)
        return (self._merge_owned(params, new_params),
                DistOptState(base_state, state.step + 1))

    def collect(self, params, *, require_mutex: bool = True):
        """Fold ALL in-flight gossip into the iterates (evaluation-time
        collect, the reference's end-of-run ``win_update_then_collect``
        usage, ``torch/mpi_ops.py:1206-1260``).

        The async step issues accumulates without a fence — at any instant a
        chunk of the network's value/P mass rides the transport, so an
        instantaneous de-bias snapshot is noisy (a rank whose mass is mostly
        in flight has tiny P and a wild ratio).  ``win_fence`` (which acks
        every peer's applied sends and ends in a barrier) guarantees no
        mass is in flight; the collect then restores exact conservation:
        gathered P sums to ``n`` and the P-weighted average equals the true
        network average."""
        W.win_fence()
        collected = [W.win_update_then_collect(name,
                                               require_mutex=require_mutex)
                     for name in self._names]
        return self._merge_owned(params, self._rebuild(collected, params))

    def associated_p(self) -> np.ndarray:
        """(n,) push-sum weight vector (identical across leaves/windows)."""
        return W.win_associated_p(self._names[0])

    def debias(self, params, *, p_min: float = 1e-3):
        """Divide each rank's slice by its associated-P scalar.

        ``p_min`` floors the divisor: under heavy scheduling skew a rank's
        P mass can be almost entirely in flight (P → 0), and dividing by it
        turns one delayed packet into inf/NaN iterates.  The floor keeps
        the estimate finite (it is inaccurate exactly when most of the
        rank's information is in flight — bound the staleness with a
        periodic :meth:`collect` for an exact de-bias).  Push-sum theory
        assumes bounded delays, under which P stays bounded away from 0
        and the floor never engages; when it DOES engage, a warning is
        logged (the clipped estimate is finite but biased — monitoring
        that watched for inf/NaN would otherwise miss it)."""
        raw = np.asarray(self.associated_p())
        p = np.maximum(raw, p_min)
        clipped = np.nonzero(raw < p_min)[0]
        if clipped.size:
            from bluefog_tpu.utils.logging import get_logger
            get_logger().warning(
                "push-sum debias: associated-P below p_min=%g for rank(s) "
                "%s — most of their mass is in flight; the de-biased "
                "estimate is clipped (finite but biased). Bound the "
                "staleness with opt.collect().", p_min, clipped.tolist())

        def div(leaf):
            shape = (-1,) + (1,) * (np.ndim(leaf) - 1)
            return leaf / jnp.asarray(p.reshape(shape), dtype=leaf.dtype)
        return jax.tree.map(div, params)
