"""Asynchronous one-sided optimizers: win_put / pull-get / push-sum.

Parity targets (reference ``torch/optimizers.py``):
  * ``_DistributedWinOptimizer`` (:844-1024) -> ``DistributedWinPutOptimizer``
    (push style) and ``DistributedPullGetOptimizer`` (pull style): named
    windows; each step pushes (or pulls) parameters along the topology's
    edges and combines via ``win_update``.
  * ``_DistributedPushSumOptimizer`` (:1026-1178) -> ``DistributedPushSumOptimizer``:
    column-stochastic ``win_accumulate`` of the parameters together with the
    push-sum weight scalar (the "associated-P" window, reference
    ``mpi_context.cc:136-156``), ``win_update_then_collect``, and de-bias
    division — converges to the network average on any strongly-connected
    digraph even though single steps are biased.

These run through the host-side window store (``bluefog_tpu.ops.window``) —
they are the *async gossip* family, deliberately outside jit: communication
overlaps compute via the store's worker pool, mirroring the reference's
nonblocking RMA + finalizer threads.  The local "adapt" math is still jitted
(vmapped over the rank axis).

Fusion: by default (``fuse=True``) the whole parameter pytree travels through
ONE window — each rank's leaves raveled into a single flat row — so a model
with hundreds of parameters issues one transport message per edge per step
instead of one per (leaf, edge).  This mirrors the collective family's
``ravel_pytree`` fusion (``optim/functional.py``) and the reference's fusion
buffer (``tensor_queue.h:70-92``); ``fuse=False`` keeps per-leaf windows (the
reference's per-parameter layout, ``torch/optimizers.py:933-944``).

Async mode (``BLUEFOG_TPU_ASYNC=1``, default off): barrier-free gossip —
the push-sum family drops its per-cadence transport fence entirely, each
rank accumulates at its own pace and every step folds only what has
arrived (associated-P corrects for in-flight mass, so the effective
operator still averages); the window layer's bounded-staleness policy
(``BLUEFOG_TPU_ASYNC_STALENESS_STEPS`` / ``_STALENESS_POLICY``) rejects
or downweights contributions older than the bound, diverting their mass
into a per-edge stale-residual store; and every
``BLUEFOG_TPU_ASYNC_COLLECT_EVERY`` steps one exact collect (fence +
residual fold) backstops the drift.  The put family steps as if
``overlap=True``; the pull family keeps its request/reply shape.  With
``=0`` nothing here changes — the lockstep path is bitwise identical.

Churn: with ``BLUEFOG_TPU_CHURN=1`` and a live gang transport, every
``step()`` drives the churn supervisor (``run/supervisor.maybe_supervisor``)
at the step boundary — failure detection, survivor re-planning and
restart-free window rebuild happen before the step's own window ops; a
committed membership change lands on ``opt.membership_change`` and an
eviction of THIS rank raises so the training loop exits cleanly.  Off
(default): one config check, the legacy path untouched.

Multi-process semantics: each process is authoritative for the ranks of its
local devices only.  ``step`` returns rank-major trees whose NON-owned rows
are frozen at their value from the previous step's input — they are never
silently installed from stale window copies (each process trains its own
ranks, exactly like the reference's one-tensor-per-process model).  Use
:meth:`gather` to materialize every rank's fresh parameters for evaluation.

Owned layout (pod scale): pass parameter trees with leading dim
``len(bf.owned_ranks())`` instead of the world size (row ``i`` = rank
``owned_ranks()[i]``) and the optimizer steps over owned rows ONLY — per-
process state is O(owned + indegree), never O(n), matching the window
layer's owned-slice storage and the reference's one-tensor-per-process
model (``torch/optimizers.py:844-1024``).  Layout is auto-detected from the
leading dim (or forced via ``layout=``); :meth:`gather` materializes the
rank-major view from either layout.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bluefog_tpu import basics
from bluefog_tpu.ops import window as W
from bluefog_tpu.optim.functional import DistOptState

__all__ = [
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
]


def _leaf_names(tree, prefix: str):
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [f"{prefix}.{jax.tree_util.keystr(p)}" for p, _ in paths]


class _WindowOptimizerBase:
    """Shared plumbing: fused (or per-leaf) windows + vmapped local update."""

    def __init__(self, base: optax.GradientTransformation, *,
                 window_prefix: str, num_steps_per_communication: int = 1,
                 fuse: bool = True, layout: str = "auto",
                 fused=None, fusion_buckets=None):
        if layout not in ("auto", "rank", "owned"):
            raise ValueError(
                f"layout must be 'auto', 'rank' or 'owned', got {layout!r}")
        self.base = base
        self.window_prefix = window_prefix
        self.num_steps_per_communication = int(num_steps_per_communication)
        self.fuse = bool(fuse)
        # Whole-step compilation (ops/fused_step.py): fused=True forces
        # the compiled step, False pins eager, None defers to
        # BLUEFOG_TPU_FUSED_STEP.  Distinct from fuse= (window fusion):
        # fuse= decides how many windows carry the tree, fused= decides
        # whether (update x concat x put) lowers into one XLA program.
        self.fused = fused
        # fusion_buckets=k partitions the fused tree over k windows
        # (contiguous, byte-balanced — optim/functional._bucket_groups)
        # so the fused program can issue one put per bucket as XLA
        # materializes it.  None keeps today's single window.
        self.fusion_buckets = fusion_buckets
        self.layout = layout
        self._layout = None   # resolved at init(): "rank" or "owned"
        self._names: List[str] = None
        self._update_fn = None
        self._fused_impl = None  # lazily-built ops.fused_step.FusedStep
        self._n = 0
        self._rows = 0        # leading dim of caller trees (n or len(owned))
        self._owned: List[int] = []
        self._shapes = None   # per-leaf (rows, *rest) shapes, fused mode
        self._dtypes = None   # per-leaf dtypes (concatenate promotes; cast back)
        self._splits = None   # np.cumsum of per-leaf flat sizes, fused mode
        self._buckets = None        # per-window leaf-index lists, fused mode
        self._bucket_splits = None  # per-window np.cumsum of leaf sizes
        # Sharded-aware gossip (ops/sharded.py): subclasses that support
        # it set shard_specs/shard_groups/num_shards; init() resolves the
        # plan.  With an active plan the fused buckets cover REPLICATED
        # leaves only and one extra "<prefix>.sharded" window carries each
        # rank's own-shard slices, put/updated over in-group edges only.
        self.shard_specs = None
        self.shard_groups = None
        self.num_shards = None
        self._shard_plan = None       # active ops.sharded.ShardPlan
        self._sharded_name = None     # the per-group window's name
        self._shard_edges = None      # {(src, dst): w} in-group put edges
        self._shard_update_kwargs = None  # win_update weight overrides
        self._shard_leaf_idx = None   # flatten indices of sharded leaves
        self._shard_dims = None       # per sharded leaf: model dim
        self._shard_sizes = None      # per sharded leaf: slice row cols

    # -- payload layout ----------------------------------------------------
    def _payloads(self, tree) -> List:
        """Row-major arrays to ship, one per window (1 when fused).

        With the zero-copy XLA put path armed (``BLUEFOG_TPU_WIN_XLA``,
        multi-process, all-f32 trees) the payloads STAY on device: the
        fused concatenate compiles into the step's program instead of a
        host ``np.concatenate``, and each window's put hands its device
        buffer straight to the native transport — the put worker blocks
        on that payload alone, so per-window (per-leaf with
        ``fuse=False``) puts are issued as the step's compiled program
        delivers each output, overlapping the remaining bucket math,
        instead of after a whole-tree host materialization.  Bitwise
        equivalent to the host path (same f32 rows, same wire frames);
        any other configuration takes the legacy numpy path."""
        # Pre-init callers (probes, tests) see the single-bucket layout;
        # init() installs the real partition before any window exists.
        n_leaves = len(jax.tree_util.tree_leaves(tree))
        buckets = (self._buckets if self._buckets is not None
                   else [list(range(n_leaves))])
        if self._device_payloads_ok(tree):
            leaves = jax.tree_util.tree_leaves(tree)
            if not self.fuse:
                return list(leaves)
            return [jnp.concatenate(
                [jnp.reshape(leaves[i], (self._rows, -1)) for i in idxs],
                axis=1) for idxs in buckets]
        leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]
        if not self.fuse:
            return leaves
        out = [np.concatenate(
            [leaves[i].reshape(self._rows, -1) for i in idxs], axis=1)
            for idxs in buckets]
        if self._shard_plan is not None:
            out.append(self._shard_payload(leaves))
        return out

    def _shard_payload(self, leaves) -> np.ndarray:
        """The sharded window's rows: per rank, its OWN shard slice of
        every sharded leaf, raveled and concatenated (same column order
        as ``_rebuild``'s inverse scatter)."""
        from bluefog_tpu.ops import sharded as SHD
        plan = self._shard_plan
        return np.concatenate(
            [SHD.own_shard_rows(leaves[i], d, plan.coords, plan.n_shards)
             for i, d in zip(self._shard_leaf_idx, self._shard_dims)],
            axis=1)

    def _device_payloads_ok(self, tree) -> bool:
        """Can this tree ship as device payloads through the XLA put
        path?  All-f32 ``jax.Array`` leaves only — the fused device
        concatenate must not change the wire dtype a mixed tree would
        get from numpy's promotion rules."""
        if self._shard_plan is not None:
            # The sharded window's payload is a host-side per-coordinate
            # slice gather; keep every payload on the one (host) path so
            # rep/sharded rows stay a single consistent snapshot.
            return False
        if W._store.distrib is None:
            return False
        from bluefog_tpu.ops import xlaffi
        if not xlaffi.armed():
            return False
        return all(isinstance(x, jax.Array) and x.dtype == jnp.float32
                   for x in jax.tree_util.tree_leaves(tree))

    def _rebuild(self, arrays: List, like):
        """Inverse of :meth:`_payloads` — back to the pytree structure.

        With an active shard plan, ``like`` must be the ADAPTED tree:
        sharded leaves take their combined own-shard slice from the
        sharded window's rows and keep ``like``'s values everywhere else
        (the other coordinates' ghost regions).  Without a plan ``like``
        supplies the tree structure only, as before."""
        treedef = jax.tree_util.tree_structure(like)
        if self.fuse:
            leaves = [None] * len(self._shapes)
            for arr, idxs, splits in zip(arrays, self._buckets,
                                         self._bucket_splits):
                flat = np.asarray(arr)
                parts = np.split(flat, splits[:-1], axis=1)
                # Cast back to each leaf's own dtype: the fused
                # concatenate promoted mixed-precision trees to a common
                # wire dtype.
                for p, i in zip(parts, idxs):
                    leaves[i] = p.reshape(self._shapes[i]).astype(
                        self._dtypes[i])
            if self._shard_plan is not None:
                from bluefog_tpu.ops import sharded as SHD
                plan = self._shard_plan
                like_leaves = jax.tree_util.tree_leaves(like)
                rows = np.asarray(arrays[-1])
                off = 0
                for i, d, sz in zip(self._shard_leaf_idx,
                                    self._shard_dims, self._shard_sizes):
                    seg = rows[:, off:off + sz]
                    off += sz
                    leaves[i] = SHD.scatter_shard_rows(
                        np.asarray(like_leaves[i]), seg, d, plan.coords,
                        plan.n_shards).astype(self._dtypes[i])
        else:
            leaves = arrays
        return jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(x) for x in leaves])

    def _merge_owned(self, prev, new):
        """Freeze non-owned rows (multi-process, rank-major layout): rows of
        ranks owned by other processes keep their previous value instead of
        receiving stale window copies.  Owned layout carries owned rows
        only, so every row is authoritative — identity."""
        if W._store.distrib is None or self._layout == "owned":
            return new
        mask = np.zeros(self._n, bool)
        mask[self._owned] = True

        def one(p, q):
            m = jnp.asarray(mask.reshape((-1,) + (1,) * (jnp.ndim(q) - 1)))
            return jnp.where(m, q, p)
        return jax.tree.map(one, prev, new)

    def gather(self, params):
        """Materialize every rank's authoritative rows in RANK-MAJOR order
        (for evaluation): allgathers owned rows across processes; identity
        single-process rank-major."""
        d = W._store.distrib
        if d is None:
            return params
        from jax.experimental import multihost_utils
        owner = np.array([d.rank_owner[r] for r in range(self._n)])
        if self._layout == "rank":
            rows = np.arange(self._n)

            def one(leaf):
                g = np.asarray(multihost_utils.process_allgather(
                    np.asarray(leaf)))
                return jnp.asarray(g[owner, rows])
            return jax.tree.map(one, params)
        # Owned layout: processes may own different rank counts (non-uniform
        # --hosts placements), and process_allgather needs uniform shapes —
        # pad each process's owned rows to the max count, gather, then take
        # rank r from (owner[r], position of r in owner[r]'s owned list).
        nproc = max(owner) + 1
        owned_of = [[r for r in range(self._n) if owner[r] == p]
                    for p in range(nproc)]
        maxrows = max(len(lst) for lst in owned_of)
        pos = np.zeros(self._n, np.int64)
        for lst in owned_of:
            for i, r in enumerate(lst):
                pos[r] = i

        def one(leaf):
            x = np.asarray(leaf)
            pad = np.zeros((maxrows - x.shape[0],) + x.shape[1:], x.dtype)
            g = np.asarray(multihost_utils.process_allgather(
                np.concatenate([x, pad], axis=0)))
            return jnp.asarray(g[owner, pos])
        return jax.tree.map(one, params)

    # -- lifecycle ---------------------------------------------------------
    def init(self, params) -> DistOptState:
        basics._require_init()
        self._n = basics.size()
        self._owned = W._owned_ranks(self._n)
        # Barrier-free async mode (BLUEFOG_TPU_ASYNC): arm the window
        # layer's bounded-staleness fold and this family's fence-free
        # stepping.  Off (default): one config check, the flag stays
        # False and every path below is bit-identical to the lockstep
        # tree.
        self._async_on = W.configure_async()
        leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(params)]
        rows = leaves[0].shape[0]
        if any(x.shape[0] != rows for x in leaves):
            raise ValueError(
                "window optimizer trees must share one leading (row) dim; "
                f"got {[x.shape[0] for x in leaves]}")
        if self.layout == "auto":
            if rows == self._n:
                self._layout = "rank"
            elif (W._store.distrib is not None
                  and rows == len(self._owned)):
                self._layout = "owned"
            else:
                raise ValueError(
                    f"{type(self).__name__}.init: leading dim {rows} is "
                    f"neither the world size ({self._n}, rank-major) nor "
                    f"this process's owned-rank count ({len(self._owned)}, "
                    "owned layout)")
        else:
            self._layout = self.layout
            want = self._n if self._layout == "rank" else len(self._owned)
            if rows != want:
                raise ValueError(
                    f"{type(self).__name__}.init: layout={self._layout!r} "
                    f"expects leading dim {want}, got {rows}")
        self._rows = rows
        self._resolve_shard_plan(params, leaves)
        plan = self._shard_plan
        if self.fuse:
            self._shapes = [x.shape for x in leaves]
            self._dtypes = [x.dtype for x in leaves]
            sizes = [int(np.prod(s[1:])) for s in self._shapes]
            self._splits = np.cumsum(sizes)
            rep_idx = (list(range(len(leaves))) if plan is None else
                       [i for i, m in enumerate(plan.mask) if not m])
            if self.fusion_buckets is not None \
                    and int(self.fusion_buckets) > 1 and rep_idx:
                from bluefog_tpu.optim.functional import _bucket_groups
                rel = _bucket_groups([leaves[i] for i in rep_idx],
                                     int(self.fusion_buckets))
                self._buckets = [[rep_idx[j] for j in grp] for grp in rel]
            else:
                self._buckets = [rep_idx] if rep_idx else []
            self._bucket_splits = [
                np.cumsum([sizes[i] for i in idxs])
                for idxs in self._buckets]
            if len(self._buckets) == 1:
                self._names = [f"{self.window_prefix}.fused"]
            else:
                self._names = [f"{self.window_prefix}.fusedb{i}"
                               for i in range(len(self._buckets))]
            if plan is not None:
                self._sharded_name = f"{self.window_prefix}.sharded"
                self._names.append(self._sharded_name)
        else:
            self._names = _leaf_names(params, self.window_prefix)
        # Owned-layout creation tensors carry no neighbor rows, so the
        # window layer cannot seed staging from them (it requires
        # zero_init).  Restore the rank layout's seeded-staging semantics
        # with one explicit identity put below instead.
        zero = self._zero_init or self._layout == "owned"
        for name, payload in zip(self._names, self._payloads(params)):
            W.win_create(payload, name, zero_init=zero)
        if self._layout == "owned" and not self._zero_init:
            for name, payload in zip(self._names, self._payloads(params)):
                W.win_put(payload, name)
            # All seeds applied everywhere before the first step's
            # win_update — otherwise it would combine zeros for edges
            # whose seed is still in flight (transient pull toward 0).
            W.win_fence()
        base = self.base

        def init_one(p):
            return base.init(p)
        st = jax.jit(jax.vmap(init_one))(jax.tree.map(jnp.asarray, params))
        self._update_fn = jax.jit(jax.vmap(
            lambda g, s, p: base.update(g, s, p)))
        return DistOptState(st, jnp.asarray(0, jnp.int32))

    def _resolve_shard_plan(self, params, leaves) -> None:
        """Arm sharded-aware gossip when shard specs were supplied, the
        knob is on, and some leaf is actually sharded; otherwise leave
        every structure ``None`` — the verbatim legacy layout."""
        self._shard_plan = None
        self._sharded_name = None
        if self.shard_specs is None:
            return
        from bluefog_tpu.utils import config as _config
        if not _config.get().sharded_gossip:
            return
        from bluefog_tpu.ops import sharded as SHD
        plan = SHD.build_plan(params, self.shard_specs, n=self._n,
                              n_shards=self.num_shards,
                              groups=self.shard_groups)
        if not plan.any_sharded:
            return
        if self._layout != "rank":
            raise ValueError(
                f"{type(self).__name__}: shard_specs requires the "
                "rank-major layout (the sharded window's per-coordinate "
                "rows are rank-indexed); owned layout is not supported")
        if not self.fuse:
            raise ValueError(
                f"{type(self).__name__}: shard_specs requires fuse=True "
                "(the sharded slices ride one dedicated fused window)")
        self._shard_plan = plan
        self._shard_leaf_idx = [i for i, m in enumerate(plan.mask) if m]
        self._shard_dims = [plan.dims[i] for i in self._shard_leaf_idx]
        self._shard_sizes = [
            int(np.prod(leaves[i].shape[1:])) // plan.n_shards
            for i in self._shard_leaf_idx]
        put_edges, self_w, nbr_w = SHD.induced_window_weights(
            plan, basics.load_topology())
        self._shard_edges = put_edges
        self._shard_update_kwargs = {
            "self_weight": self_w, "neighbor_weights": nbr_w}

    def _local_adapt(self, params, grads, state: DistOptState):
        updates, base_state = self._update_fn(grads, state.base, params)
        new_params = jax.tree.map(lambda p, u: p + u, params, updates)
        return new_params, base_state

    # -- whole-step compilation (ops/fused_step.py) ------------------------
    def _fused_wanted(self) -> bool:
        """Does this step even attempt the fused lowering?  One config
        check when the constructor deferred (``fused=None``) — with
        ``BLUEFOG_TPU_FUSED_STEP=0`` nothing fused is ever imported,
        built or registered (the inertness contract)."""
        if self.fused is False:
            return False
        if self.fused is True:
            return True
        from bluefog_tpu.utils import config
        return bool(config.get().fused_step)

    def _fused_try_step(self, params, grads, state: DistOptState, *,
                        family: str, dst_weights=None, self_weight=None,
                        require_mutex: bool = False, pre_drain=None):
        """Run one step through the compiled fused program, or return
        None (after one logged warning per reason) when this
        configuration cannot take the fused path — the caller then runs
        the eager step, which stays the bitwise oracle."""
        from bluefog_tpu.ops import fused_step as fused_mod
        if self._fused_impl is None:
            self._fused_impl = fused_mod.FusedStep(self)
        try:
            return self._fused_impl.step(
                params, grads, state, family=family,
                dst_weights=dst_weights, self_weight=self_weight,
                require_mutex=require_mutex, pre_drain=pre_drain)
        except fused_mod.FusedFallback:
            return None

    # Latest committed membership change observed by _maybe_churn_step
    # (None until the gang churns); `evicted` mirrors the supervisor's
    # verdict for THIS rank.
    membership_change = None
    evicted = False

    def _maybe_churn_step(self, t: int) -> None:
        """Drive the churn supervisor at this step boundary
        (``BLUEFOG_TPU_CHURN=1`` + a live multi-process transport;
        otherwise a no-op after one cheap config check).  The PR 7
        follow-up: training loops no longer have to step the supervisor
        manually — every window-family ``step()`` feeds it, so failure
        detection, survivor re-planning and restart-free window rebuild
        happen before this step's window ops run.  A committed change
        lands in :attr:`membership_change`; if THIS rank was voted out,
        :attr:`evicted` flips and a RuntimeError tells the loop to exit
        (gossiping on as a ghost would wedge the survivors' fences).

        Defers to a MANUALLY-constructed supervisor: when a live
        controller exists that the process-wide singleton does not own
        (chaos harness, custom loops calling ``ChurnSupervisor()``
        directly), its owner is already stepping it — spawning a second
        supervisor here would double-heartbeat and race recoveries."""
        from bluefog_tpu.run import supervisor as sup_mod
        from bluefog_tpu.utils import config as _config
        if not _config.get().churn:
            return
        from bluefog_tpu.ops import membership
        cur = membership.current()
        if cur is not None and (sup_mod._singleton is None
                                or sup_mod._singleton.ctrl is not cur):
            return
        sup = sup_mod.maybe_supervisor()
        if sup is None:
            return
        view = sup.step(t)
        if view is None:
            return
        self.membership_change = view
        if view.evicted:
            self.evicted = True
            raise RuntimeError(
                f"{type(self).__name__}.step: this rank was evicted by "
                f"membership consensus (epoch {view.epoch}); exit the "
                "training loop — the survivors have re-planned without it")

    _async_on = False

    def _async_step_begin(self, t: int) -> None:
        """Async-mode step bookkeeping: publish my step clock (staleness
        ages count against it; both trace-tag encoders stamp it as the
        wire origin step) and the ``bf_async_step_lag{rank}`` gauge — my
        step vs the freshest peer step seen through sampled tags.
        No-op outside async mode."""
        if not self._async_on:
            return
        W.set_async_step(t)
        from bluefog_tpu.utils import telemetry
        telemetry.set_gauge("bf_async_step_lag", float(W.async_step_lag()),
                            rank=str(basics.rank()))

    def _async_collect_due(self, t: int) -> bool:
        """True when this async step is the periodic exact-collect
        backstop (``BLUEFOG_TPU_ASYNC_COLLECT_EVERY``): fence the
        transport, fold the stale residuals back in, collect exactly —
        bounding both the parameter drift and the step lag a straggler
        can accumulate (fast ranks wait here, and only here)."""
        if not self._async_on or W._store.distrib is None:
            return False
        from bluefog_tpu.utils import config as _config
        every = _config.get().async_collect_every
        return every > 0 and (t + 1) % every == 0

    @staticmethod
    def _step_timer():
        from bluefog_tpu.utils import telemetry
        return telemetry.start_timer()

    def _record_step_time(self, t0, t: int) -> None:
        """Step-latency histogram for the async family (the host-side step
        IS the true wall time — window ops complete before return), plus
        the periodic cross-rank straggler gather
        (``BLUEFOG_TPU_PROFILE`` / ``BLUEFOG_TPU_PROFILE_EVERY``).  The
        gather is collective; every process runs the same step loop, so
        the periods line up — same contract as the consensus sampler."""
        from bluefog_tpu.utils import profiler, telemetry
        dt = telemetry.observe_since(t0, "bf_optimizer_step_seconds",
                                     family="window")
        if dt is None:
            return
        pe = profiler.profile_period()
        if pe and (t + 1) % pe == 0:
            outer = profiler.active()
            if outer is not None:
                # An enclosing bf.step_profile() records this step itself;
                # just make sure exactly one straggler gather happens.
                outer.request_straggler()
            else:
                profiler.record_synced_step(dt)

    def _maybe_sample_consensus(self, t: int, payloads, combined) -> None:
        """Consensus-distance gauge for the async family: every K steps
        (``BLUEFOG_TPU_TELEMETRY_CONSENSUS_EVERY``) record, per owned rank,
        the L2 distance between the locally adapted parameters (``payloads``,
        pre-combine) and the ``win_update`` result (``combined``, the
        weighted neighborhood mean) — the same gossip-health signal the
        collective family samples, read off the combine this step already
        performed (zero extra communication)."""
        from bluefog_tpu.utils import telemetry
        k = telemetry.consensus_every()
        if not k or (t + 1) % k:
            return
        sq = None
        for pre, post in zip(payloads, combined):
            diff = (np.asarray(pre, np.float32)
                    - np.asarray(post, np.float32))
            diff = diff.reshape(diff.shape[0], -1)
            s = np.einsum("ij,ij->i", diff, diff)
            sq = s if sq is None else sq + s
        dist = np.sqrt(sq)
        if self._layout == "rank" and W._store.distrib is not None:
            dist = dist[self._owned]  # non-owned rows are zero-filled
        telemetry.record_consensus_distance(float(dist.mean()),
                                            float(dist.max()))

    def free(self):
        # Flush the transport's send queues first: a coalesced edge payload
        # still lingering in a per-peer queue when its window dies here
        # would land at the peer as gossip for a window we no longer track.
        # Best-effort — teardown must complete even when a peer is dead,
        # and promptly even when one is wedged (the legacy free()
        # succeeded locally regardless of peers), hence the short timeout.
        try:
            W.win_flush(timeout=5.0)
        except Exception:  # noqa: BLE001 — never abort cleanup
            from bluefog_tpu.utils.logging import get_logger
            get_logger().warning(
                "window optimizer free(): transport flush failed "
                "(dead peer?); continuing teardown", exc_info=True)
        for name in self._names or []:
            W.win_free(name)
        self._names = None

    def _quiesce(self) -> None:
        """Complete every in-flight window op this optimizer issued (and,
        multi-process, fence the transport) so a snapshot cannot miss
        queued or in-flight gossip mass."""
        if W._store.distrib is not None:
            # Flush-before-fence: queued coalesced sends reach TCP first,
            # so the fence's acks certify THEM applied too (the fence also
            # flushes internally — this surfaces send errors at the
            # snapshot call site instead of inside the fence wait).
            W.win_flush()
            W.win_fence()

    def _require_windows(self, what: str):
        if not self._names:
            raise RuntimeError(
                f"{type(self).__name__}.{what}: no windows exist — call "
                "init() first (and not after free()); a silent empty "
                "snapshot would lose all gossip state")
        return self._names

    def window_state_dict(self):
        """Snapshot every window this optimizer owns (checkpoint-ready
        numpy tree keyed by window name; pair with
        :meth:`load_window_state_dict` after re-``init`` on restart so
        in-staging gossip mass survives elastic restarts).  Quiesces
        in-flight ops first — overlapped puts and transport-in-flight
        mass land before the snapshot.

        Multi-process: COLLECTIVE — the quiesce fences the transport
        (``win_fence`` ends in a barrier), so every process must call
        this (and :meth:`load_window_state_dict`) together, like the
        reference's collective window ops."""
        names = self._require_windows("window_state_dict")
        self._quiesce()
        return {name: W.win_state_dict(name) for name in names}

    def load_window_state_dict(self, state) -> None:
        names = set(self._require_windows("load_window_state_dict"))
        self._quiesce()  # an in-flight put landing after the restore
        #                  would corrupt the just-restored state
        snap = dict(state)
        if set(snap) != names:
            raise ValueError(
                f"{type(self).__name__}.load_window_state_dict: snapshot "
                f"windows {sorted(snap)} do not match this optimizer's "
                f"{sorted(names)} — was the snapshot taken with a "
                "different fuse= setting or window_prefix?")
        for name, s in snap.items():
            W.win_load_state_dict(name, s)

    _zero_init = False


class DistributedWinPutOptimizer(_WindowOptimizerBase):
    """Push-style async optimizer: adapt locally, ``win_put`` the new
    parameters to out-neighbors, combine received neighbor state via
    ``win_update`` (reference factory ``torch/optimizers.py:1271``).

    ``step(..., dst_weights=...)`` takes the same weight forms as
    ``bf.win_put`` and is re-resolvable every call (dynamic topologies).

    ``overlap=True`` makes the put genuinely asynchronous: ``step`` issues
    the nonblocking put and returns WITHOUT waiting — the put executes on
    the worker pool while the caller computes the next forward/backward,
    and the next step's ``win_update`` combines whatever has arrived (one
    extra step of staleness, the reference's actual async operating mode:
    its win optimizers overlapped RMA with compute via hooks,
    ``torch/optimizers.py:889-909``).  The previous put is always waited
    before the next one is issued, so per-window ordering holds even with
    a multi-worker pool.

    Note that in overlap mode the rank's OWN row lags too, not just the
    neighbors': a put self-publishes the adapted parameters into the local
    window, so when step ``t+1``'s ``win_update`` runs before step ``t``'s
    put has landed, the combine is taken over step ``t-1``'s published
    self value — step ``t``'s local adapt result reaches the combined
    state one step late, same as its neighbors see it."""

    def __init__(self, base, *, window_prefix: str = "winput",
                 num_steps_per_communication: int = 1, fuse: bool = True,
                 overlap: bool = False, layout: str = "auto",
                 fused=None, fusion_buckets=None,
                 shard_specs=None, shard_groups=None, num_shards=None):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication,
                         fuse=fuse, layout=layout, fused=fused,
                         fusion_buckets=fusion_buckets)
        self.overlap = bool(overlap)
        # Sharded-aware gossip (ops/sharded.py, same contract as the
        # collective family's DistributedOptimizer kwargs): sharded
        # leaves ride a dedicated window whose puts and update weights
        # are restricted to in-replica-group edges.
        self.shard_specs = shard_specs
        self.shard_groups = shard_groups
        self.num_shards = None if num_shards is None else int(num_shards)
        self._pending: List[int] = []

    def step(self, params, grads, state: DistOptState, *,
             dst_weights=None, require_mutex: bool = True):
        t0 = self._step_timer()
        self._maybe_churn_step(int(state.step))
        self._async_step_begin(int(state.step))
        t = int(state.step)
        comm = (t + 1) % self.num_steps_per_communication == 0
        if comm and self._fused_wanted():
            out = self._fused_try_step(params, grads, state, family="put",
                                       dst_weights=dst_weights,
                                       require_mutex=require_mutex)
            if out is not None:
                self._record_step_time(t0, t)
                return out
        new_params, base_state = self._local_adapt(params, grads, state)
        if comm:
            # Ordering: the previous overlapped put must complete before a
            # new one targets the same window.
            self._drain_pending()
            payloads = self._payloads(new_params)
            handles = [
                W.win_put_nonblocking(
                    payload, name,
                    # The sharded window's puts cross in-group edges
                    # only — its slices must never leave the replica
                    # group that shares their coordinate.
                    dst_weights=(self._shard_edges
                                 if name == self._sharded_name
                                 else dst_weights),
                    require_mutex=require_mutex)
                for name, payload in zip(self._names, payloads)]
            # Async mode implies overlap: the put must not block the
            # step on a slow peer's wire — the next step's win_update
            # combines whatever has arrived (the put family's natural
            # barrier-free operating mode; the staleness policy and the
            # residual store are push-sum/accumulate concepts and do not
            # apply to overwrite puts).
            if self.overlap or self._async_on:
                # Overlapped puts flush themselves when their worker-pool
                # job finishes; kick the transport NOW (non-blocking — the
                # per-peer senders flush on their own threads) so gossip
                # already enqueued rides the wire during the next
                # forward/backward instead of waiting out the linger.
                W.win_flush(wait=False)
                self._pending = handles
            else:
                for h in handles:
                    W.win_wait(h)
            combined = [
                W.win_update(name, require_mutex=require_mutex,
                             # Explicit partial weights: out-of-group
                             # staging (if any ever landed) stays pending
                             # and never leaks into the sharded average.
                             **(self._shard_update_kwargs
                                if name == self._sharded_name else {}))
                for name in self._names]
            self._maybe_sample_consensus(t, payloads, combined)
            new_params = self._rebuild(combined, new_params)
        out = (self._merge_owned(params, new_params),
               DistOptState(base_state, state.step + 1))
        self._record_step_time(t0, t)
        return out

    def _drain_pending(self) -> None:
        for h in self._pending:   # overlapped puts must land first
            W.win_wait(h)
        self._pending = []

    def free(self):
        self._drain_pending()
        super().free()

    def _quiesce(self) -> None:
        self._drain_pending()
        super()._quiesce()


class DistributedPullGetOptimizer(_WindowOptimizerBase):
    """Pull-style async optimizer: adapt locally, publish self memory, then
    ``win_get`` neighbors' parameters and combine (reference factory
    ``torch/optimizers.py:1225``)."""

    def __init__(self, base, *, window_prefix: str = "pullget",
                 num_steps_per_communication: int = 1, fuse: bool = True,
                 layout: str = "auto"):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication,
                         fuse=fuse, layout=layout)

    def step(self, params, grads, state: DistOptState, *,
             src_weights=None, require_mutex: bool = True):
        t0 = self._step_timer()
        self._maybe_churn_step(int(state.step))
        # Pull-style steps stay request/reply (a get cannot fold "whatever
        # arrived" — it asks NOW), but the step clock + lag gauge still
        # publish so a pull gang's telemetry shows who runs ahead.
        self._async_step_begin(int(state.step))
        new_params, base_state = self._local_adapt(params, grads, state)
        t = int(state.step)
        if (t + 1) % self.num_steps_per_communication == 0:
            payloads = self._payloads(new_params)
            # Publish my new parameters as the window's exposed memory (the
            # dst_weights={} put touches no edges — it only refreshes main).
            publish = [W.win_put_nonblocking(payload, name,
                                             self_weight=1.0, dst_weights={})
                       for name, payload in zip(self._names, payloads)]
            for h in publish:
                W.win_wait(h)
            handles = [W.win_get_nonblocking(name, src_weights=src_weights,
                                             require_mutex=require_mutex)
                       for name in self._names]
            for h in handles:
                W.win_wait(h)
            combined = [W.win_update(name, require_mutex=require_mutex)
                        for name in self._names]
            self._maybe_sample_consensus(t, payloads, combined)
            new_params = self._rebuild(combined, params)
        out = (self._merge_owned(params, new_params),
               DistOptState(base_state, state.step + 1))
        self._record_step_time(t0, t)
        return out


class DistributedPushSumOptimizer(_WindowOptimizerBase):
    """Async push-sum gossip SGD (reference factory
    ``torch/optimizers.py:1180``).

    Every step: local adapt, column-stochastic ``win_accumulate`` of the raw
    parameters (each rank splits weight ``1/(outdeg+1)`` over itself and its
    out-neighbors), ``win_update_then_collect``, and the associated-P scalar
    tracks the accumulated weight so ``debias`` recovers unbiased iterates.
    Gradients should be evaluated at ``debias(params)``.
    """

    _zero_init = True

    def __init__(self, base, *, window_prefix: str = "pushsum",
                 num_steps_per_communication: int = 1, fuse: bool = True,
                 layout: str = "auto", auto_collect_rounds: int = 8,
                 fused=None, fusion_buckets=None):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication,
                         fuse=fuse, layout=layout, fused=fused,
                         fusion_buckets=fusion_buckets)
        self.auto_collect_rounds = int(auto_collect_rounds)

    def init(self, params) -> DistOptState:
        W.turn_on_win_ops_with_associated_p()
        return super().init(params)

    def _outgoing_weights(self) -> Dict[int, float]:
        topo = basics.load_topology()
        n = basics.size()
        from bluefog_tpu import topology as topology_util
        w = {}
        for r in range(n):
            outs = topology_util.out_neighbor_ranks(topo, r)
            share = 1.0 / (len(outs) + 1.0)
            for o in outs:
                w[(r, o)] = share
        return w

    def _self_share(self) -> np.ndarray:
        topo = basics.load_topology()
        n = basics.size()
        from bluefog_tpu import topology as topology_util
        return np.array([
            1.0 / (len(topology_util.out_neighbor_ranks(topo, r)) + 1.0)
            for r in range(n)])

    def step(self, params, grads, state: DistOptState, *,
             dst_weights=None, require_mutex: bool = True):
        t0 = self._step_timer()
        self._maybe_churn_step(int(state.step))
        self._async_step_begin(int(state.step))
        if dst_weights is None:
            dst_weights = self._outgoing_weights()
        self_share = self._self_share()
        t = int(state.step)
        if self._fused_wanted():
            fence_due = (not self._async_on
                         and self.auto_collect_rounds > 0
                         and W._store.distrib is not None
                         and (t + 1) % self.auto_collect_rounds == 0)
            backstop_due = self._async_collect_due(t)

            def _pre_drain():
                if fence_due or backstop_due:
                    W.win_fence()
                    if backstop_due:
                        for name in self._names:
                            W.win_fold_stale_residuals(name)
            out = self._fused_try_step(params, grads, state,
                                       family="pushsum",
                                       dst_weights=dst_weights,
                                       self_weight=self_share,
                                       require_mutex=require_mutex,
                                       pre_drain=_pre_drain)
            if out is not None:
                self._record_step_time(t0, t)
                return out
        new_params, base_state = self._local_adapt(params, grads, state)
        # Flow control, lockstep mode: every ``auto_collect_rounds``
        # communication rounds the step fences the transport before
        # folding — no process can run more than that many rounds ahead of
        # a stalled peer (the fence is a barrier), so the fraction of a
        # rank's P mass that can ever be in flight is bounded and de-bias
        # stays well-conditioned WITHOUT caller-side periodic collect().
        # The reference gets the analogous bound for free from MPI's
        # passive-target progress/ordering (``mpi_controller.cc:953-1034``);
        # a TCP transport must make it explicit.  The fence is collective —
        # every process calls step the same number of times (the SPMD
        # training loop), so the fences line up.  auto_collect_rounds=0
        # disables.
        #
        # Async mode (BLUEFOG_TPU_ASYNC=1) replaces this coupling
        # entirely: NO per-cadence fence — ranks accumulate at their own
        # pace, the fold takes whatever has arrived (push-sum associated-P
        # corrects for in-flight mass), the bounded-staleness policy
        # rejects/downweights over-age contributions into the stale-
        # residual store, and the only barrier left is the periodic exact
        # collect (``BLUEFOG_TPU_ASYNC_COLLECT_EVERY``) that folds those
        # residuals back in — a straggler costs its contributions'
        # freshness, not the fleet's throughput.
        fence_now = (not self._async_on
                     and self.auto_collect_rounds > 0
                     and W._store.distrib is not None
                     and (t + 1) % self.auto_collect_rounds == 0)
        backstop_now = self._async_collect_due(t)
        handles = []
        payloads = self._payloads(new_params)
        for name, payload in zip(self._names, payloads):
            # win_accumulate applies self_weight AFTER the edge sends, so the
            # out-edges carry w * p_old and per-source mass
            # (self_share + sum_out w == 1) is conserved — the push-sum
            # column-stochastic invariant.
            handles.append(W.win_accumulate_nonblocking(
                payload, name, self_weight=self_share,
                dst_weights=dst_weights, require_mutex=require_mutex))
        for h in handles:
            W.win_wait(h)
        if fence_now or backstop_now:
            W.win_fence()
            if backstop_now:
                # Post-fence nothing is in flight: folding the stale
                # residuals here and collecting restores EXACT push-sum
                # conservation, including every contribution the
                # staleness policy held back since the last backstop.
                for name in self._names:
                    W.win_fold_stale_residuals(name)
        collected = [W.win_update_then_collect(name,
                                               require_mutex=require_mutex)
                     for name in self._names]
        self._maybe_sample_consensus(t, payloads, collected)
        new_params = self._rebuild(collected, params)
        out = (self._merge_owned(params, new_params),
               DistOptState(base_state, state.step + 1))
        self._record_step_time(t0, t)
        return out

    def collect(self, params, *, require_mutex: bool = True):
        """Fold ALL in-flight gossip into the iterates (evaluation-time
        collect, the reference's end-of-run ``win_update_then_collect``
        usage, ``torch/mpi_ops.py:1206-1260``).

        The async step issues accumulates without a fence — at any instant a
        chunk of the network's value/P mass rides the transport, so an
        instantaneous de-bias snapshot is noisy (a rank whose mass is mostly
        in flight has tiny P and a wild ratio).  ``win_fence`` (which acks
        every peer's applied sends and ends in a barrier) guarantees no
        mass is in flight; the collect then restores exact conservation:
        gathered P sums to ``n`` and the P-weighted average equals the true
        network average."""
        W.win_fence()
        # Async mode: the bounded-staleness policy may be holding
        # rejected/downweighted mass in the stale-residual store — fold
        # it back in post-fence so THIS collect is exact too (no-op with
        # empty stores, i.e. always outside async mode).
        for name in self._names:
            W.win_fold_stale_residuals(name)
        collected = [W.win_update_then_collect(name,
                                               require_mutex=require_mutex)
                     for name in self._names]
        return self._merge_owned(params, self._rebuild(collected, params))

    def associated_p(self) -> np.ndarray:
        """(n,) push-sum weight vector (identical across leaves/windows)."""
        return W.win_associated_p(self._names[0])

    def debias(self, params, *, p_min: float = 1e-3):
        """Divide each rank's slice by its associated-P scalar.

        ``p_min`` floors the divisor: under heavy scheduling skew a rank's
        P mass can be almost entirely in flight (P → 0), and dividing by it
        turns one delayed packet into inf/NaN iterates.  The floor keeps
        the estimate finite (it is inaccurate exactly when most of the
        rank's information is in flight — bound the staleness with a
        periodic :meth:`collect` for an exact de-bias).  Push-sum theory
        assumes bounded delays, under which P stays bounded away from 0
        and the floor never engages; when it DOES engage, a warning is
        logged (the clipped estimate is finite but biased — monitoring
        that watched for inf/NaN would otherwise miss it)."""
        raw = np.asarray(self.associated_p())
        row_rank = np.arange(raw.shape[0])  # row index -> global rank
        if self._layout == "owned":
            # Owned-layout trees carry owned rows only; pick their P slots
            # (associated_p is always global-rank indexed).
            row_rank = np.asarray(self._owned, dtype=np.int64)
            raw = raw[row_rank]
        p = np.maximum(raw, p_min)
        clipped = np.nonzero(raw < p_min)[0]
        if clipped.size:
            from bluefog_tpu.utils.logging import get_logger
            get_logger().warning(
                "push-sum debias: associated-P below p_min=%g for rank(s) "
                "%s — most of their mass is in flight; the de-biased "
                "estimate is clipped (finite but biased). Bound the "
                "staleness with opt.collect().", p_min,
                row_rank[clipped].tolist())

        def div(leaf):
            shape = (-1,) + (1,) * (np.ndim(leaf) - 1)
            return leaf / jnp.asarray(p.reshape(shape), dtype=leaf.dtype)
        return jax.tree.map(div, params)
