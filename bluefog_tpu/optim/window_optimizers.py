"""Asynchronous one-sided optimizers: win_put / pull-get / push-sum.

Parity targets (reference ``torch/optimizers.py``):
  * ``_DistributedWinOptimizer`` (:844-1024) -> ``DistributedWinPutOptimizer``
    (push style) and ``DistributedPullGetOptimizer`` (pull style): per-parameter
    named windows; each step pushes (or pulls) parameters along the topology's
    edges and combines via ``win_update``.
  * ``_DistributedPushSumOptimizer`` (:1026-1178) -> ``DistributedPushSumOptimizer``:
    column-stochastic ``win_accumulate`` of the parameters together with the
    push-sum weight scalar (the "associated-P" window, reference
    ``mpi_context.cc:136-156``), ``win_update_then_collect``, and de-bias
    division — converges to the network average on any strongly-connected
    digraph even though single steps are biased.

These run through the host-side window store (``bluefog_tpu.ops.window``) —
they are the *async gossip* family, deliberately outside jit: communication
overlaps compute via the store's worker pool, mirroring the reference's
nonblocking RMA + finalizer threads.  The local "adapt" math is still jitted
(vmapped over the rank axis).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bluefog_tpu import basics
from bluefog_tpu.ops import window as W
from bluefog_tpu.optim.functional import DistOptState

__all__ = [
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
]


def _leaf_names(tree, prefix: str):
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [f"{prefix}.{jax.tree_util.keystr(p)}" for p, _ in paths]


class _WindowOptimizerBase:
    """Shared plumbing: per-leaf windows + vmapped local base update."""

    def __init__(self, base: optax.GradientTransformation, *,
                 window_prefix: str, num_steps_per_communication: int = 1):
        self.base = base
        self.window_prefix = window_prefix
        self.num_steps_per_communication = int(num_steps_per_communication)
        self._names = None
        self._update_fn = None

    def init(self, params) -> DistOptState:
        basics._require_init()
        self._names = _leaf_names(params, self.window_prefix)
        for name, leaf in zip(self._names,
                              jax.tree_util.tree_leaves(params)):
            W.win_create(np.asarray(leaf), name, zero_init=self._zero_init)
        base = self.base

        def init_one(p):
            return base.init(p)
        st = jax.jit(jax.vmap(init_one))(jax.tree.map(jnp.asarray, params))
        self._update_fn = jax.jit(jax.vmap(
            lambda g, s, p: base.update(g, s, p)))
        return DistOptState(st, jnp.asarray(0, jnp.int32))

    def _local_adapt(self, params, grads, state: DistOptState):
        updates, base_state = self._update_fn(grads, state.base, params)
        new_params = jax.tree.map(lambda p, u: p + u, params, updates)
        return new_params, base_state

    def free(self):
        for name in self._names or []:
            W.win_free(name)
        self._names = None

    _zero_init = False


class DistributedWinPutOptimizer(_WindowOptimizerBase):
    """Push-style async optimizer: adapt locally, ``win_put`` the new
    parameters to out-neighbors, combine received neighbor state via
    ``win_update`` (reference factory ``torch/optimizers.py:1271``).

    ``step(..., dst_weights=...)`` takes the same weight forms as
    ``bf.win_put`` and is re-resolvable every call (dynamic topologies)."""

    def __init__(self, base, *, window_prefix: str = "winput",
                 num_steps_per_communication: int = 1):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication)

    def step(self, params, grads, state: DistOptState, *,
             dst_weights=None, require_mutex: bool = True):
        new_params, base_state = self._local_adapt(params, grads, state)
        t = int(state.step)
        if (t + 1) % self.num_steps_per_communication == 0:
            handles = [
                W.win_put_nonblocking(np.asarray(leaf), name,
                                      dst_weights=dst_weights,
                                      require_mutex=require_mutex)
                for name, leaf in zip(self._names,
                                      jax.tree_util.tree_leaves(new_params))]
            for h in handles:
                W.win_wait(h)
            combined = [W.win_update(name, require_mutex=require_mutex)
                        for name in self._names]
            treedef = jax.tree_util.tree_structure(params)
            new_params = jax.tree_util.tree_unflatten(treedef, combined)
        return new_params, DistOptState(base_state, state.step + 1)


class DistributedPullGetOptimizer(_WindowOptimizerBase):
    """Pull-style async optimizer: adapt locally, publish self memory, then
    ``win_get`` neighbors' parameters and combine (reference factory
    ``torch/optimizers.py:1225``)."""

    def __init__(self, base, *, window_prefix: str = "pullget",
                 num_steps_per_communication: int = 1):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication)

    def step(self, params, grads, state: DistOptState, *,
             src_weights=None, require_mutex: bool = True):
        new_params, base_state = self._local_adapt(params, grads, state)
        t = int(state.step)
        if (t + 1) % self.num_steps_per_communication == 0:
            # Publish my new parameters as the window's exposed memory (the
            # dst_weights={} put touches no edges — it only refreshes main).
            publish = [W.win_put_nonblocking(np.asarray(leaf), name,
                                             self_weight=1.0, dst_weights={})
                       for name, leaf in zip(
                           self._names, jax.tree_util.tree_leaves(new_params))]
            for h in publish:
                W.win_wait(h)
            handles = [W.win_get_nonblocking(name, src_weights=src_weights,
                                             require_mutex=require_mutex)
                       for name in self._names]
            for h in handles:
                W.win_wait(h)
            combined = [W.win_update(name, require_mutex=require_mutex)
                        for name in self._names]
            treedef = jax.tree_util.tree_structure(params)
            new_params = jax.tree_util.tree_unflatten(treedef, combined)
        return new_params, DistOptState(base_state, state.step + 1)


class DistributedPushSumOptimizer(_WindowOptimizerBase):
    """Async push-sum gossip SGD (reference factory
    ``torch/optimizers.py:1180``).

    Every step: local adapt, column-stochastic ``win_accumulate`` of the raw
    parameters (each rank splits weight ``1/(outdeg+1)`` over itself and its
    out-neighbors), ``win_update_then_collect``, and the associated-P scalar
    tracks the accumulated weight so ``debias`` recovers unbiased iterates.
    Gradients should be evaluated at ``debias(params)``.
    """

    _zero_init = True

    def __init__(self, base, *, window_prefix: str = "pushsum",
                 num_steps_per_communication: int = 1):
        super().__init__(base, window_prefix=window_prefix,
                         num_steps_per_communication=num_steps_per_communication)

    def init(self, params) -> DistOptState:
        W.turn_on_win_ops_with_associated_p()
        return super().init(params)

    def _outgoing_weights(self) -> Dict[int, float]:
        topo = basics.load_topology()
        n = basics.size()
        from bluefog_tpu import topology as topology_util
        w = {}
        for r in range(n):
            outs = topology_util.out_neighbor_ranks(topo, r)
            share = 1.0 / (len(outs) + 1.0)
            for o in outs:
                w[(r, o)] = share
        return w

    def _self_share(self) -> np.ndarray:
        topo = basics.load_topology()
        n = basics.size()
        from bluefog_tpu import topology as topology_util
        return np.array([
            1.0 / (len(topology_util.out_neighbor_ranks(topo, r)) + 1.0)
            for r in range(n)])

    def step(self, params, grads, state: DistOptState, *,
             dst_weights=None, require_mutex: bool = True):
        new_params, base_state = self._local_adapt(params, grads, state)
        if dst_weights is None:
            dst_weights = self._outgoing_weights()
        self_share = self._self_share()
        collected = []
        for name, leaf in zip(self._names,
                              jax.tree_util.tree_leaves(new_params)):
            # win_accumulate applies self_weight AFTER the edge sends, so the
            # out-edges carry w * p_old and per-source mass
            # (self_share + sum_out w == 1) is conserved — the push-sum
            # column-stochastic invariant.
            h = W.win_accumulate_nonblocking(
                np.asarray(leaf), name, self_weight=self_share,
                dst_weights=dst_weights, require_mutex=require_mutex)
            W.win_wait(h)
            collected.append(W.win_update_then_collect(
                name, require_mutex=require_mutex))
        treedef = jax.tree_util.tree_structure(params)
        new_params = jax.tree_util.tree_unflatten(treedef, collected)
        return new_params, DistOptState(base_state, state.step + 1)

    def associated_p(self) -> np.ndarray:
        """(n,) push-sum weight vector (identical across leaves)."""
        return W.win_associated_p(self._names[0])

    def debias(self, params):
        """Divide each rank's slice by its associated-P scalar."""
        p = np.asarray(self.associated_p())

        def div(leaf):
            shape = (-1,) + (1,) * (np.ndim(leaf) - 1)
            return leaf / jnp.asarray(p.reshape(shape), dtype=leaf.dtype)
        return jax.tree.map(div, params)
