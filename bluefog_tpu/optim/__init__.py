"""Distributed optimizers over optax (reference layer L6,
``bluefog/torch/optimizers.py``).

Two levels:
  * ``bluefog_tpu.optim.functional`` — pure per-rank step functions for use
    inside your own ``shard_map``/``pjit`` training step (the TPU-idiomatic
    path; zero host round-trips).
  * The ``Distributed*Optimizer`` classes below — eager parity surface over
    rank-major pytrees, matching the reference's eight factories.
"""

from bluefog_tpu.optim.functional import (  # noqa: F401
    CommunicationType,
    DistOptState,
    awc_step,
    atc_step,
    gradient_allreduce_step,
    dist_init,
    make_combiner,
    step_fn,
)
from bluefog_tpu.optim.optimizers import (  # noqa: F401
    DistributedOptimizer,
    DistributedGradientAllreduceOptimizer,
    DistributedAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
    DistributedHierarchicalNeighborAllreduceOptimizer,
    DistributedHierarchicalGossipOptimizer,
    DistributedAdaptWithCombineOptimizer,
    DistributedAdaptThenCombineOptimizer,
)
from bluefog_tpu.optim.window_optimizers import (  # noqa: F401
    DistributedWinPutOptimizer,
    DistributedPullGetOptimizer,
    DistributedPushSumOptimizer,
)
