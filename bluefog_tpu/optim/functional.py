"""Per-rank distributed-optimizer step functions (the functional core).

Every function here is pure and designed to run *inside* ``jax.shard_map`` /
``pjit`` over the rank mesh axis, so the whole training step — forward,
backward, base-optimizer math and the decentralized communication — is one XLA
program per device.  This replaces the reference's hook machinery
(``torch/optimizers.py``): where BlueFog splices communication into torch
autograd via forward/backward hooks and synchronizes handles in ``step()``,
here the communication is just another op in the traced step.

Execution orders (reference ``torch/optimizers.py:311-320`` theory note):
  AWC (adapt-with-combine, ``_DistributedReduceOptimizer:297-483``):
      ``x_{t+1} = combine(x_t) + base_update(g_t)``
  ATC (adapt-then-combine, ``_DistributedAdaptThenCombineOptimizer:485-842``):
      ``x_{t+1} = combine(x_t + base_update(g_t))``
  gradient allreduce (``_DistributedOptimizer:166-295``):
      ``x_{t+1} = x_t + base_update(allreduce(g_t))``

``combine`` is any of: global allreduce-average (consensus), static/dynamic
neighbor averaging, hierarchical machine-level averaging, or identity
("empty").  Local aggregation — communicate only every J-th step
(``optimizers.py:348-350``) — is a ``lax.cond`` on the traced step counter, so
one compiled program serves both communicating and silent steps.
"""

from __future__ import annotations

import enum
from functools import partial
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from bluefog_tpu.ops import collective as C
from bluefog_tpu.ops.schedule import DynamicSchedule, StaticSchedule

__all__ = [
    "CommunicationType",
    "DistOptState",
    "make_combiner",
    "make_shard_combiner",
    "compress_combiner",
    "awc_step",
    "atc_step",
    "gradient_allreduce_step",
]


class CommunicationType(enum.Enum):
    """Parity: reference ``torch/optimizers.py:28-34`` (plus the TPU-only
    two-level gossip of ``BLUEFOG_TPU_HIER``)."""
    allreduce = "allreduce"
    neighbor_allreduce = "neighbor.allreduce"
    hierarchical_neighbor_allreduce = "hierarchical.neighbor.allreduce"
    hierarchical_gossip = "hierarchical.gossip"
    empty = "empty"


class DistOptState(NamedTuple):
    base: optax.OptState
    step: jnp.ndarray            # int32 scalar, counts optimizer steps
    acc: Optional[object] = None  # grad accumulator (gradient_allreduce, J>1)


Combiner = Callable[..., jnp.ndarray]  # (x, *, step, weights) -> x


def make_combiner(
        comm: CommunicationType,
        *,
        axis_name: str,
        sched: Optional[StaticSchedule] = None,
        dyn_sched: Optional[DynamicSchedule] = None,
        local_axis: Optional[str] = None,
        machine_axis: Optional[str] = None,
        hier: Optional[dict] = None,
) -> Combiner:
    """Build the per-leaf ``combine`` function for a communication type.

    The returned callable has signature ``combine(x, step, weights)`` where
    ``step`` is the traced step counter (used by dynamic schedules) and
    ``weights`` is an optional traced (n, n) matrix overriding the static
    schedule's weights (None => baked-in weights).
    """
    def _no_weights(weights, what):
        if weights is not None:
            raise ValueError(
                f"per-step weight overrides are not supported for {what}; "
                "they apply to (dynamic) neighbor_allreduce only")

    if comm == CommunicationType.empty:
        def _empty(x, step=None, weights=None):
            _no_weights(weights, "CommunicationType.empty")
            return x
        _empty.is_identity = True  # lets _tree_combine skip fusion copies
        return _empty
    if comm == CommunicationType.allreduce:
        def _ar(x, step=None, weights=None):
            _no_weights(weights, "CommunicationType.allreduce")
            return C.allreduce(x, axis_name, average=True)
        _ar.is_allreduce = True  # replica-identical: compress without residual
        return _ar
    if comm == CommunicationType.neighbor_allreduce:
        if dyn_sched is not None:
            def _dyn(x, step, weights=None):
                if weights is None:
                    return C.dynamic_neighbor_allreduce(
                        x, step, dyn_sched, axis_name)
                # Weight override on a dynamic topology: same phase switching,
                # weights looked up from the traced matrix per active edge.
                branches = [
                    partial(lambda ph, args: C.neighbor_allreduce_matrix(
                        args[0], args[1], ph, axis_name), ph)
                    for ph in dyn_sched.phases]
                return lax.switch(step % dyn_sched.period, branches,
                                  (x, weights))
            # Lets compress_combiner run the aligned rotating-block sparse
            # exchange under the same lax.switch of phases
            # (compression="sparse:<frac>" on dynamic topologies).
            _dyn._sparse_dyn_args = (dyn_sched, axis_name)
            return _dyn
        assert sched is not None, "static neighbor_allreduce needs a schedule"

        def _nbr(x, step=None, weights=None):
            if weights is None:
                return C.neighbor_allreduce(x, sched, axis_name)
            return C.neighbor_allreduce_matrix(x, weights, sched, axis_name)
        # Lets compress_combiner build the top-k SPARSE exchange over the
        # same compiled edge schedule (compression="sparse:<frac>").
        _nbr._sparse_args = (sched, axis_name)
        return _nbr
    if comm == CommunicationType.hierarchical_gossip:
        assert local_axis and machine_axis, \
            "hierarchical gossip needs local/machine axis names"
        assert hier is not None, \
            "hierarchical gossip needs the compiled level bundle (hier=)"

        def _hgossip(x, step, weights=None):
            _no_weights(weights, "hierarchical_gossip")
            return C.hierarchical_gossip(
                x, step, hier["inner_sched"], hier["outer_scheds"],
                local_axis=local_axis, machine_axis=machine_axis,
                outer_every=hier.get("outer_every", 1),
                outer_compression=hier.get("outer_compression", "none"),
                outer_frac=hier.get("outer_frac"))
        return _hgossip
    if comm == CommunicationType.hierarchical_neighbor_allreduce:
        assert local_axis and machine_axis, \
            "hierarchical combine needs local/machine axis names"
        if dyn_sched is not None:
            def _hdyn(x, step, weights=None):
                _no_weights(weights, "hierarchical_neighbor_allreduce")
                return C.dynamic_hierarchical_neighbor_allreduce(
                    x, step, dyn_sched, local_axis, machine_axis)
            return _hdyn
        assert sched is not None

        def _hier(x, step=None, weights=None):
            _no_weights(weights, "hierarchical_neighbor_allreduce")
            return C.hierarchical_neighbor_allreduce(
                x, sched, local_axis, machine_axis)
        return _hier
    raise ValueError(f"unknown communication type {comm}")


def make_shard_combiner(plan, group_combine, *, axis_name: str):
    """Per-replica-group combiner for the sharded leaves of a plan.

    ``plan`` is an :class:`ops.sharded.ShardPlan`; ``group_combine`` is a
    regular combiner (``make_combiner`` output, optionally wrapped by
    ``compress_combiner``) built over the plan's *merged group schedule*
    — its in-group-only edges are what keeps sharded bytes off the DCN.

    The returned callable runs inside ``shard_map`` on the sharded
    sub-list of leaves (flatten order): each rank slices its *own* shard
    chunk along the leaf's sharded model dim, ravels the slices into one
    buffer, gossips it over the group schedule, and writes the combined
    slice back — the other coordinates' ghost values stay untouched, so
    ranks never average slices they don't own."""
    from jax.flatten_util import ravel_pytree
    coords = jnp.asarray(plan.coords, jnp.int32)
    sh_dims = tuple(d for m, d in zip(plan.mask, plan.dims) if m)

    def shard_combine(leaves, step=None):
        # Runs on the per-rank block (rank-major leading dim already
        # stripped by shard_map), so the sharded model dim d IS array
        # axis d here — the host-side +1 offset applies only to the
        # rank-major tree the plan was built from.
        if not leaves:
            return leaves
        coord = coords[lax.axis_index(axis_name)]
        slices = []
        for leaf, d in zip(leaves, sh_dims):
            chunk = leaf.shape[d] // plan.n_shards
            slices.append(lax.dynamic_slice_in_dim(
                leaf, coord * chunk, chunk, axis=d))
        flat, unravel = ravel_pytree(slices)
        combined = unravel(group_combine(flat, step=step, weights=None))
        out = []
        for leaf, d, sl in zip(leaves, sh_dims, combined):
            chunk = leaf.shape[d] // plan.n_shards
            out.append(lax.dynamic_update_slice_in_dim(
                leaf, sl.astype(leaf.dtype), coord * chunk, axis=d))
        return out
    return shard_combine


def _bucket_groups(leaves, fusion_buckets: Optional[int]):
    """Partition flatten-order leaf indices into contiguous fusion buckets.

    ``fusion_buckets`` (explicit count) wins over the
    ``BLUEFOG_TPU_FUSION_BUCKET_MB`` size cap; with neither, one bucket —
    today's whole-tree ravel.  Buckets are contiguous in tree-flatten
    order, byte-balanced (count mode) or size-capped (MB mode), and
    deterministic: every SPMD rank must build identical buffers.
    """
    from bluefog_tpu.utils import config
    nbytes = [int(np.prod(l.shape)) * l.dtype.itemsize for l in leaves]
    total = sum(nbytes)
    if fusion_buckets is not None:
        k = max(1, min(int(fusion_buckets), len(leaves)))
        if k == 1:
            return [list(range(len(leaves)))]
        # Close bucket b once the running total crosses b/k of the bytes:
        # balanced without look-ahead, never more than k buckets.
        groups, cur, cum, b = [], [], 0, 1
        for i, nb in enumerate(nbytes):
            cur.append(i)
            cum += nb
            if cum * k >= b * total and b < k:
                groups.append(cur)
                cur, b = [], b + 1
        if cur:
            groups.append(cur)
        return groups
    cap = config.get().fusion_bucket_mb * (1 << 20)
    if cap <= 0:
        return [list(range(len(leaves)))]
    groups, cur, cur_bytes = [], [], 0
    for i, nb in enumerate(nbytes):
        if cur and cur_bytes + nb > cap:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        groups.append(cur)
    return groups


def _fused_apply(fn, tree, fusion_buckets: Optional[int]):
    """Apply ``fn`` (flat-array -> flat-array) to a pytree through fusion
    buckets: each bucket of leaves ravels into one flat buffer, so a model
    with hundreds of parameters issues one collective set per bucket
    instead of one per parameter.  With multiple buckets the per-bucket
    programs are INDEPENDENT subgraphs — bucket i+1's producer math carries
    no data dependency on bucket i's collective, so XLA's latency-hiding
    scheduler overlaps wire time with compute (the single-buffer ravel
    serializes ALL producers before the first ppermute can start)."""
    from jax.flatten_util import ravel_pytree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    groups = _bucket_groups(leaves, fusion_buckets)
    if len(groups) == 1:
        flat, unravel = ravel_pytree(tree)
        return unravel(fn(flat))
    out = list(leaves)
    for grp in groups:
        flat, unravel = ravel_pytree([leaves[i] for i in grp])
        for i, leaf in zip(grp, unravel(fn(flat))):
            out[i] = leaf
    return jax.tree_util.tree_unflatten(treedef, out)


def _tree_combine(params, combine, step, weights, steps_per_comm: int,
                  fuse: bool = True, fusion_buckets: Optional[int] = None,
                  shard_plan=None, shard_combine=None):
    """Apply ``combine`` to a pytree, skipping steps where
    ``step % steps_per_comm != 0`` (local aggregation).

    ``fuse=True`` ravels the tree into fusion-bucket buffers (default: one)
    so a model with hundreds of parameters issues one ppermute set per
    round per bucket instead of one per parameter — the TPU-native
    replacement for the reference's FusionBufferManager + fused-response
    machinery (``tensor_queue.h:70-92``, ``operations.cc:918-1001``), with
    zero copy-in/copy-out phases because XLA fuses the concatenation into
    the collective's producers/consumers.  ``fusion_buckets > 1`` (or the
    ``BLUEFOG_TPU_FUSION_BUCKET_MB`` cap) splits the buffer so per-bucket
    communication pipelines against the other buckets' optimizer math —
    see :func:`_fused_apply`.

    With an active ``shard_plan`` (a plan whose mask marks some leaves
    sharded) the tree is split by the mask: replicated leaves ride the
    legacy fused path over the full topology, sharded leaves go through
    ``shard_combine`` (:func:`make_shard_combiner`) — per-replica-group
    gossip of each rank's own shard slice.  Without an active plan this
    function is byte-for-byte the legacy replicated-only path, which is
    what keeps fully replicated trees bit-identical under the knob.
    """
    sharded_on = (shard_plan is not None and shard_combine is not None
                  and shard_plan.any_sharded)
    if not sharded_on:
        if getattr(combine, "is_identity", False):
            return params  # empty communication: no fusion copies, no cond

        def comm_all(p):
            if fuse:
                return _fused_apply(
                    lambda flat: combine(flat, step=step, weights=weights),
                    p, fusion_buckets)
            return jax.tree.map(
                lambda x: combine(x, step=step, weights=weights), p)
        if steps_per_comm == 1:
            return comm_all(params)
        # lax.cond keeps one compiled program; both branches cheap to trace.
        return lax.cond(step % steps_per_comm == 0, comm_all,
                        lambda p: p, params)

    rep_idx = [i for i, m in enumerate(shard_plan.mask) if not m]
    sh_idx = [i for i, m in enumerate(shard_plan.mask) if m]

    def comm_all(p):
        leaves, treedef = jax.tree_util.tree_flatten(p)
        out = list(leaves)
        if rep_idx and not getattr(combine, "is_identity", False):
            rep = [leaves[i] for i in rep_idx]
            if fuse:
                rep_out = _fused_apply(
                    lambda flat: combine(flat, step=step, weights=weights),
                    rep, fusion_buckets)
            else:
                rep_out = [combine(x, step=step, weights=weights)
                           for x in rep]
            for i, leaf in zip(rep_idx, rep_out):
                out[i] = leaf
        sh_out = shard_combine([leaves[i] for i in sh_idx], step=step)
        for i, leaf in zip(sh_idx, sh_out):
            out[i] = leaf
        return jax.tree_util.tree_unflatten(treedef, out)
    if steps_per_comm == 1:
        return comm_all(params)
    # lax.cond keeps one compiled program; both branches are cheap to trace.
    return lax.cond(step % steps_per_comm == 0, comm_all, lambda p: p, params)


def awc_step(base: optax.GradientTransformation, combine: Combiner,
             params, grads, state: DistOptState, *,
             weights=None, steps_per_comm: int = 1, fuse: bool = True,
             fusion_buckets: Optional[int] = None,
             shard_plan=None, shard_combine=None):
    """Adapt-with-combine: communicate params, then apply the base update.

    Matches ``_DistributedReduceOptimizer`` (reference
    ``torch/optimizers.py:297-483``): the forward hook launches communication
    of ``x_t`` while backward computes ``g_t``; ``step()`` waits and applies
    the local update to the *combined* parameters.  With ``fusion_buckets``
    the base update of bucket i overlaps the combine of bucket i+1 (each
    bucket's update depends only on its own combine).
    """
    combined = _tree_combine(params, combine, state.step, weights,
                             steps_per_comm, fuse, fusion_buckets,
                             shard_plan, shard_combine)
    updates, base_state = base.update(grads, state.base, combined)
    new_params = optax.apply_updates(combined, updates)
    return new_params, DistOptState(base_state, state.step + 1)


def atc_step(base: optax.GradientTransformation, combine: Combiner,
             params, grads, state: DistOptState, *,
             weights=None, steps_per_comm: int = 1, fuse: bool = True,
             fusion_buckets: Optional[int] = None,
             shard_plan=None, shard_combine=None):
    """Adapt-then-combine: local base update first, then communicate.

    Matches ``_DistributedAdaptThenCombineOptimizer`` (reference
    ``torch/optimizers.py:485-842``) — which re-implements sgd/adam/rmsprop/
    adagrad/adadelta by hand to fuse the update into the backward hook; here
    any optax transformation slots in unchanged.  With ``fusion_buckets``
    bucket i's combine can hit the wire as soon as ITS leaves' updates are
    applied, overlapping the remaining buckets' optimizer math.
    """
    updates, base_state = base.update(grads, state.base, params)
    half = optax.apply_updates(params, updates)
    new_params = _tree_combine(half, combine, state.step, weights,
                               steps_per_comm, fuse, fusion_buckets,
                               shard_plan, shard_combine)
    return new_params, DistOptState(base_state, state.step + 1)


def compress_combiner(combine: Combiner, compression: str,
                      *, residual: bool = True,
                      steps_per_comm: int = 1) -> Combiner:
    """Wrap a combiner so its payload crosses the wire compressed.

    ``"bf16"`` casts to bfloat16 before the collective and back after —
    half the ICI/DCN bytes per round, the role of the reference family's
    fp16 compression (Horovod-style; BlueFog inherits the float16 wire
    type, ``common/half.h``).  ``"none"`` returns the combiner unchanged.

    ``residual=True`` (parameter-consensus orders) adds back the local
    quantization residual ``x - q(x)`` after combining — difference
    compression: the error becomes ``(W - I)(q(x) - x)`` instead of
    ``W (q(x) - x)``, so a rank's own f32 master weights are never
    truncated by its own round trips (with ``combine = identity`` the
    wrapper is exact).  Set ``residual=False`` where every rank must apply
    the bit-identical result (synchronous gradient averaging).
    """
    if compression in (None, "none"):
        return combine
    if isinstance(compression, str) and (compression.startswith("sparse")
                                         or compression.startswith("topk")):
        if compression.startswith("topk"):
            raise ValueError(
                "magnitude-only top-k gossip does not converge under the "
                "stateless per-round residual (never-picked coordinates "
                "stay unmixed forever); use compression='sparse:<frac>' — "
                "a step-rotating aligned block that sweeps every "
                "coordinate and reaches EXACT consensus")
        # "sparse:<frac>": ship only ceil(frac*size) entries per round —
        # (k,) values + (k,) int32 indices per edge instead of the dense
        # payload (C.sparse_neighbor_allreduce).  The index block ROTATES
        # with the step and is IDENTICAL on every rank, so each round is
        # exact dense gossip restricted to the block and a full sweep
        # covers every coordinate in ceil(1/frac) rounds — block-
        # coordinate gossip.  The per-round residual x - q keeps the
        # unsent coordinates locally intact; mass conservation is exact
        # and consensus reaches machine precision (measured; magnitude-
        # only top-k selection instead STALLS, because per-rank picks
        # disagree and never-picked coordinates never mix).
        if ":" not in compression:
            raise ValueError(
                f"malformed {compression!r}: use 'sparse:<frac>' "
                "(e.g. 'sparse:0.25')")
        try:
            frac = float(compression.split(":", 1)[1])
        except ValueError:
            raise ValueError(
                f"malformed {compression!r}: the fraction must be a "
                "float in (0, 1], e.g. 'sparse:0.25'") from None
        if not 0.0 < frac <= 1.0:
            raise ValueError(
                f"sparse fraction must be in (0, 1], got {frac}")
        if getattr(combine, "is_identity", False):
            return combine  # empty communication: string validated above
        args = getattr(combine, "_sparse_args", None)
        dyn_args = getattr(combine, "_sparse_dyn_args", None)
        if args is None and dyn_args is None:
            raise ValueError(
                "compression='sparse:<frac>' needs a (static or dynamic) "
                "neighbor_allreduce combiner (the sparse exchange rides "
                "the compiled edge schedule); use 'bf16' for the other "
                "communication types")
        if not residual:
            raise ValueError(
                "sparse compression requires residual error feedback "
                "(decentralized orders); it cannot keep an allreduce "
                "replica-identical")

        def wrapped_sparse(x, step=None, weights=None):
            if weights is not None:
                raise ValueError(
                    "per-step weight overrides are not supported under "
                    "sparse compression (weights are baked into the "
                    "sparse schedule)")
            kk = max(1, int(np.ceil(frac * x.size)))
            s = jnp.asarray(0 if step is None else step, jnp.int32)
            # Rotate by the COMMUNICATION-round index: with local
            # aggregation (steps_per_comm J > 1) the combiner only runs
            # when step % J == 0, and rotating by the raw step would
            # alias to multiples of gcd(J*kk, size) — entire coordinate
            # blocks would never cross the wire.
            rnd_idx = s // max(1, int(steps_per_comm))
            rot = ((jnp.arange(kk, dtype=jnp.int32) + rnd_idx * kk)
                   % x.size)
            if args is not None:
                sched, axis_name = args
                out, q = C.sparse_neighbor_allreduce(
                    x, sched, axis_name, indices=rot, aligned=True,
                    return_sent=True)
            else:
                dyn_sched, axis_name = dyn_args
                out, q = C.dynamic_sparse_neighbor_allreduce(
                    x, s, dyn_sched, axis_name, indices=rot,
                    return_sent=True)
            return out + (x - q)
        return wrapped_sparse
    if compression != "bf16":
        raise ValueError(f"unknown compression {compression!r}; "
                         "expected 'none', 'bf16' or 'sparse:<frac>'")
    if getattr(combine, "is_identity", False):
        return combine  # keep _tree_combine's identity fast path

    def wrapped(x, **kw):
        q = x.astype(jnp.bfloat16)
        out = combine(q, **kw).astype(x.dtype)
        if residual:
            out = out + (x - q.astype(x.dtype))
        return out
    return wrapped


def gradient_allreduce_step(base: optax.GradientTransformation,
                            params, grads, state: DistOptState, *,
                            axis_name: str, steps_per_comm: int = 1,
                            compression: str = "none", fuse: bool = True,
                            fusion_buckets: Optional[int] = None):
    """Horovod-style synchronous gradient averaging
    (reference ``_DistributedOptimizer``, ``torch/optimizers.py:166-295``).

    With ``steps_per_comm > 1`` gradients accumulate locally on silent steps
    and the J-step aggregate is averaged and applied on communicating steps
    only — every rank always applies the identical update, preserving the
    replica-identical invariant (the reference's delayed-allreduce counters,
    ``torch/optimizers.py:348-383``).

    ``fuse``/``fusion_buckets`` ride the same bucket machinery as the
    parameter-consensus orders; for a uniform-dtype gradient tree the fused
    averaging is bit-identical to per-leaf (psum and the bf16 casts are
    elementwise), it just issues one allreduce per bucket instead of one
    per gradient leaf.  Mixed-dtype trees stay on the per-leaf path: the
    ravel would promote every leaf to a common dtype, changing the psum
    rounding — this order's replica-identical numerics must not shift
    underneath existing runs.
    """
    # residual=False: every rank must apply the bit-identical averaged
    # gradient (the replica-identical invariant below).
    one = compress_combiner(
        lambda x, **kw: C.allreduce(x, axis_name, average=True),
        compression, residual=False)
    uniform_dtype = len(
        {l.dtype for l in jax.tree_util.tree_leaves(grads)}) <= 1

    def comm(g):
        if fuse and uniform_dtype:
            return _fused_apply(one, g, fusion_buckets)
        return jax.tree.map(one, g)
    if steps_per_comm == 1:
        avg = comm(grads)
        updates, base_state = base.update(avg, state.base, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, DistOptState(base_state, state.step + 1)

    acc = state.acc if state.acc is not None else \
        jax.tree.map(jnp.zeros_like, grads)
    acc = jax.tree.map(lambda a, g: a + g, acc, grads)

    def communicate(_):
        avg = comm(acc)
        updates, base_state = base.update(avg, state.base, params)
        return (optax.apply_updates(params, updates), base_state,
                jax.tree.map(jnp.zeros_like, acc))

    def silent(_):
        return params, state.base, acc

    new_params, base_state, new_acc = lax.cond(
        (state.step + 1) % steps_per_comm == 0, communicate, silent, None)
    return new_params, DistOptState(base_state, state.step + 1, new_acc)


def dist_init(base: optax.GradientTransformation, params) -> DistOptState:
    return DistOptState(base.init(params), jnp.asarray(0, jnp.int32))


def step_fn(order: str, base: optax.GradientTransformation,
            combine: Combiner, *, axis_name: str,
            steps_per_comm: int = 1, fuse: bool = True,
            fusion_buckets: Optional[int] = None,
            compression: str = "none",
            residual: Optional[bool] = None,
            shard_plan=None, shard_combine=None) -> Callable:
    """Bind an execution order to a ``(params, grads, state[, weights])`` fn.

    ``fusion_buckets`` splits the fused communication buffer into that many
    byte-balanced buckets (None: one bucket, or the
    ``BLUEFOG_TPU_FUSION_BUCKET_MB`` size cap when set) so per-bucket
    collectives pipeline against the other buckets' optimizer math.

    ``residual`` controls difference compression under ``compression='bf16'``.
    A global-consensus allreduce must keep replicas bit-identical, so the
    per-rank quantization residual is NOT re-added after combining (with
    residual the drift is bf16-scale and re-averaged each round, but the
    replica-identical invariant is worth more than the residual's accuracy
    for that order); decentralized combiners keep difference compression.
    Callers that know the communication type should pass this explicitly
    (``optim.optimizers`` does); with ``None`` it falls back to the
    ``is_allreduce`` tag ``make_combiner`` sets."""
    if residual is None:
        residual = not getattr(combine, "is_allreduce", False)
    combine = compress_combiner(combine, compression, residual=residual,
                                steps_per_comm=steps_per_comm)
    if order == "awc":
        return partial(awc_step, base, combine,
                       steps_per_comm=steps_per_comm, fuse=fuse,
                       fusion_buckets=fusion_buckets,
                       shard_plan=shard_plan, shard_combine=shard_combine)
    if order == "atc":
        return partial(atc_step, base, combine,
                       steps_per_comm=steps_per_comm, fuse=fuse,
                       fusion_buckets=fusion_buckets,
                       shard_plan=shard_plan, shard_combine=shard_combine)
    if order == "gradient_allreduce":
        if shard_plan is not None and shard_plan.any_sharded:
            raise ValueError(
                "sharded gossip applies to the parameter-consensus orders "
                "(awc/atc); gradient allreduce averages gradients globally "
                "and cannot restrict sharded leaves to replica groups")
        return partial(gradient_allreduce_step, base, axis_name=axis_name,
                       steps_per_comm=steps_per_comm,
                       compression=compression, fuse=fuse,
                       fusion_buckets=fusion_buckets)
    raise ValueError(f"unknown execution order {order!r}")
