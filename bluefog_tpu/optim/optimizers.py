"""Distributed optimizer classes — the ``bf.Distributed*Optimizer`` surface.

Parity target: the eight factory functions of reference
``torch/optimizers.py:1180-1554``.  Where the reference wraps a
``torch.optim.Optimizer`` instance and splices communication in via autograd
hooks, these wrap an ``optax.GradientTransformation`` and compile the whole
step — communication included — into one jitted ``shard_map`` program over the
rank mesh.

Data model: parameters/gradients are pytrees of *rank-major* arrays (leading
dim == ``bf.size()``), the same single-controller convention as the eager op
API in ``bluefog_tpu.basics``.  ``init`` returns optimizer state whose leaves
are rank-major too (each rank carries its own moments), so the entire training
loop stays device-resident.

Usage::

    opt = bf.optim.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    state = opt.init(params)
    params, state = opt.step(params, grads, state)

Dynamic topology (one-peer Exp2 etc.)::

    opt = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1), use_dynamic_topology=True)
    # phase auto-advances with state.step; no recompilation per step.

Per-step weight mutation (reference README.rst:110-127 mutates
``opt.self_weight``/``opt.neighbor_weights``): pass ``self_weight=...,
src_weights=...`` kwargs to ``step`` — they become *traced* inputs, so
changing them every iteration never recompiles.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from bluefog_tpu import basics
from bluefog_tpu import topology as topology_util
from bluefog_tpu.basics import LOCAL_AXIS, MACHINE_AXIS, RANK_AXIS
from bluefog_tpu.ops import schedule as S
from bluefog_tpu.optim import functional as F
from bluefog_tpu.optim.functional import CommunicationType, DistOptState

__all__ = [
    "CommunicationType",
    "DistributedOptimizer",
    "DistributedGradientAllreduceOptimizer",
    "DistributedAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
    "DistributedHierarchicalNeighborAllreduceOptimizer",
    "DistributedHierarchicalGossipOptimizer",
    "DistributedAdaptWithCombineOptimizer",
    "DistributedAdaptThenCombineOptimizer",
]


class DistributedOptimizer:
    """Generic decentralized optimizer wrapper (see module docstring).

    Parameters
    ----------
    base : optax.GradientTransformation
    communication_type : CommunicationType
    order : "awc" | "atc" | "gradient_allreduce"
    num_steps_per_communication : communicate every J-th step (local
        aggregation, reference ``torch/optimizers.py:348-350``).
    use_dynamic_topology : cycle the one-peer phase table of the active
        topology (or ``phases`` if given) by step index.
    phases : explicit list of ``topology.DynamicPhase`` for dynamic mode.
    fusion_buckets : split the fused communication buffer into this many
        byte-balanced buckets so each bucket's collectives overlap the
        other buckets' optimizer math (AWC: update(i) || combine(i+1);
        ATC: combine(i) || update(i+1)).  ``None``: one bucket — unless
        ``BLUEFOG_TPU_FUSION_BUCKET_MB`` caps bucket size instead.  Only
        meaningful with ``fusion=True``; tune when the model is large
        enough that parameter communication and step math are comparable
        (see docs/performance.md).
    donate : donate the grads and state buffers to the jitted step so XLA
        aliases them into the outputs (grads, same tree shape as params,
        becomes the new params buffer) — peak memory drops by roughly one
        full parameter set (decisive for billion-parameter models on one
        chip).  The caller must NOT reuse the grads or state it passed in
        after ``step`` returns (the usual ``params, state =
        opt.step(params, grads, state)`` rebinding pattern is safe; the
        params argument itself is not donated).
    shard_specs : tree of *model*-dimension ``PartitionSpec``s matching
        the params structure (``parallel.tensor_parallel.tp_param_specs``
        output) that arms sharded-aware gossip (``ops/sharded.py``,
        ``BLUEFOG_TPU_SHARDED_GOSSIP``): leaves whose spec names a mesh
        axis gossip their per-rank shard slice inside the replica group
        holding the same shard coordinate, while replicated leaves ride
        the full topology — per-step DCN bytes drop to the replicated
        fraction of the tree.  Requires ``neighbor_allreduce`` with an
        awc/atc order.  ``None`` (default): today's replicated-only path,
        bit for bit.
    shard_groups : explicit replica groups (iterable of rank iterables
        partitioning ``range(n)``); default: ``num_shards`` contiguous
        blocks.
    num_shards : shard count along each sharded model dim (groups =
        contiguous rank blocks).  Required when ``shard_specs`` marks any
        leaf sharded and ``shard_groups`` is not given.
    profile_every : every N steps, block until the step's device work
        completes, record the TRUE step wall time into the step-profiler
        histograms and gather every rank's duration into a straggler
        report (``bf_straggler_score``, surfaced in ``/healthz`` and
        ``%bfstat``).  The synced sample costs one host sync + one tiny
        allgather per period, so it is opt-in: ``None`` defers to
        ``BLUEFOG_TPU_PROFILE`` / ``BLUEFOG_TPU_PROFILE_EVERY``; 0
        disables outright.  COLLECTIVE in multi-process runs (every
        process steps the same loop, so the periods line up).
    """

    def __init__(self, base: optax.GradientTransformation,
                 communication_type: CommunicationType =
                 CommunicationType.neighbor_allreduce,
                 *, order: str = "awc",
                 num_steps_per_communication: int = 1,
                 use_dynamic_topology: bool = False,
                 phases=None, fusion: bool = True,
                 fusion_buckets: Optional[int] = None,
                 compression: str = "none", donate: bool = False,
                 profile_every: Optional[int] = None,
                 shard_specs=None, shard_groups=None,
                 num_shards: Optional[int] = None):
        if isinstance(communication_type, str):
            communication_type = CommunicationType(communication_type)
        if compression not in ("none", "bf16") and not (
                isinstance(compression, str)
                and compression.startswith(("sparse", "topk"))):
            raise ValueError(f"unknown compression {compression!r}; "
                             "expected 'none', 'bf16' or 'sparse:<frac>'")
        self.base = base
        self.communication_type = communication_type
        self.order = order
        self.num_steps_per_communication = int(num_steps_per_communication)
        self.use_dynamic_topology = use_dynamic_topology
        self.phases = phases
        if fusion_buckets is not None and int(fusion_buckets) < 1:
            raise ValueError(f"fusion_buckets must be >= 1, got {fusion_buckets}")
        # Fused communication buffers (reference FusionBufferManager);
        # fusion_buckets > 1 pipelines per-bucket comm against step math.
        self.fusion = fusion
        self.fusion_buckets = (None if fusion_buckets is None
                               else int(fusion_buckets))
        # "bf16": halve the wire bytes per round (functional.
        # compress_combiner — the reference family's fp16 compression role).
        self.compression = compression
        self.donate = donate
        if profile_every is not None and int(profile_every) < 0:
            raise ValueError(
                f"profile_every must be >= 0, got {profile_every}")
        self.profile_every = (None if profile_every is None
                              else int(profile_every))
        if shard_specs is not None:
            if communication_type != CommunicationType.neighbor_allreduce:
                raise ValueError(
                    "shard_specs requires CommunicationType."
                    "neighbor_allreduce (sharded leaves gossip per replica "
                    f"group over the compiled schedule), got "
                    f"{communication_type}")
            if order not in ("awc", "atc"):
                raise ValueError(
                    "shard_specs requires a parameter-consensus order "
                    f"(awc/atc), got {order!r}")
        self.shard_specs = shard_specs
        self.shard_groups = shard_groups
        self.num_shards = None if num_shards is None else int(num_shards)
        self._jitted = {}
        self._steps_seen = 0  # host-side counter for telemetry sampling
        self._hier_meta = None   # set by _hier_gossip_bundle
        self._hier_step0 = None  # state.step of the first hier step seen
        self._shard_plan_cache = {}  # (treedef, shapes) -> ShardPlan
        self._shard_meta_cache = {}  # telemetry edge counts per plan/topo
        self._shard_step0 = None  # state.step of the first sharded step

    # -- schedule resolution ------------------------------------------------
    def _schedules(self):
        ctx = basics._require_init()
        hier = (self.communication_type ==
                CommunicationType.hierarchical_neighbor_allreduce)
        topo = ctx.machine_topology if hier else ctx.topology
        weighted = ctx.is_machine_topo_weighted if hier else ctx.is_topo_weighted
        if topo is None:
            raise RuntimeError("no (machine) topology installed; call bf.init()")
        n = topo.number_of_nodes()
        if self.use_dynamic_topology:
            version = (ctx.machine_topology_version if hier
                       else ctx.topology_version)
            key = ("opt_dyn", version,
                   None if self.phases is None
                   else tuple(tuple(ph.pairs) for ph in self.phases))
            phases = self.phases
            return None, ctx.static_schedule(key, lambda: S.compile_dynamic(
                phases if phases is not None
                else topology_util.dynamic_phase_table(topo), n))
        version = (ctx.machine_topology_version if hier
                   else ctx.topology_version)
        key = ("opt_static", version, weighted)
        return ctx.static_schedule(
            key, lambda: S.compile_static(topo, use_topo_weights=weighted)), None

    # -- sharded-gossip plan resolution ------------------------------------
    def _shard_plan(self, params):
        """Resolve (and cache) the sharded-gossip plan for this tree.

        Returns ``None`` — the verbatim legacy path — unless shard specs
        were supplied AND ``BLUEFOG_TPU_SHARDED_GOSSIP`` is on.  The plan
        is cached by (treedef, shapes, dtypes): the mask depends on leaf
        shapes (indivisible dims fall back to replicated)."""
        from bluefog_tpu.utils import config
        if self.shard_specs is None or not config.get().sharded_gossip:
            return None
        leaves, treedef = jax.tree_util.tree_flatten(params)
        key = (treedef,
               tuple((tuple(l.shape), str(np.dtype(l.dtype)))
                     for l in leaves))
        plan = self._shard_plan_cache.get(key)
        if plan is None:
            from bluefog_tpu.ops import sharded as SH
            plan = SH.build_plan(
                params, self.shard_specs, n=basics.size(),
                n_shards=self.num_shards, groups=self.shard_groups)
            self._shard_plan_cache[key] = plan
        return plan

    def _group_schedule(self, ctx, plan):
        """Merged per-replica-group schedule for ``plan`` (cached on the
        context like every other compiled schedule; the key carries the
        sharding signature so re-sharding re-prices)."""
        from bluefog_tpu.ops import sharded as SH
        return ctx.static_schedule(
            ("opt_sharded", ctx.topology_version, plan.signature),
            lambda: SH.compile_group_schedules(plan.n, plan.groups))

    def _shard_telemetry_meta(self, plan):
        """(replicated-ici, replicated-dcn, in-group) edge counts for the
        per-shard byte accounting, memoized per (topology, plan)."""
        from bluefog_tpu.ops import sharded as SH
        ctx = basics._require_init()
        key = (ctx.topology_version, plan.signature,
               self.use_dynamic_topology)
        meta = self._shard_meta_cache.get(key)
        if meta is None:
            sched, dyn = self._schedules()
            rep_ici, rep_dcn = SH.edge_level_counts(
                plan.coords, sched if sched is not None else dyn)
            grp_edges = 0.0
            if plan.any_sharded:
                gsched, _per_group = self._group_schedule(ctx, plan)
                grp_edges = float(
                    sum(len(r.pairs) for r in gsched.rounds))
            meta = (rep_ici, rep_dcn, grp_edges)
            self._shard_meta_cache[key] = meta
        return meta

    def _build_step(self, with_weights: bool, plan=None):
        ctx = basics._require_init()
        hier = (self.communication_type in (
                CommunicationType.hierarchical_neighbor_allreduce,
                CommunicationType.hierarchical_gossip))
        sched, dyn = (None, None)
        if self.communication_type in (
                CommunicationType.neighbor_allreduce,
                CommunicationType.hierarchical_neighbor_allreduce):
            sched, dyn = self._schedules()
        hier_bundle = None
        if self.communication_type == CommunicationType.hierarchical_gossip:
            hier_bundle = self._hier_gossip_bundle(ctx)
        combine = F.make_combiner(
            self.communication_type,
            axis_name=RANK_AXIS if not hier else MACHINE_AXIS,
            sched=sched, dyn_sched=dyn,
            local_axis=LOCAL_AXIS if hier else None,
            machine_axis=MACHINE_AXIS if hier else None,
            hier=hier_bundle)
        shard_combine = None
        if plan is not None and plan.any_sharded:
            # The sharded leaves' combiner gossips each rank's own shard
            # slice over the merged per-group schedule; compression
            # composes exactly as on the replicated combiner.
            gsched, _per_group = self._group_schedule(ctx, plan)
            gc = F.make_combiner(
                CommunicationType.neighbor_allreduce,
                axis_name=RANK_AXIS, sched=gsched)
            gc = F.compress_combiner(
                gc, self.compression, residual=True,
                steps_per_comm=self.num_steps_per_communication)
            shard_combine = F.make_shard_combiner(
                plan, gc, axis_name=RANK_AXIS)
        inner = F.step_fn(
            self.order, self.base, combine,
            axis_name=RANK_AXIS,
            steps_per_comm=self.num_steps_per_communication,
            fuse=self.fusion, fusion_buckets=self.fusion_buckets,
            compression=self.compression,
            # Explicit residual policy: a global-consensus allreduce must
            # stay replica-bit-identical under compression.
            residual=(self.communication_type
                      != CommunicationType.allreduce),
            shard_plan=plan, shard_combine=shard_combine)
        mesh = ctx.hier_mesh if hier else ctx.mesh
        spec = P((MACHINE_AXIS, LOCAL_AXIS)) if hier else P(RANK_AXIS)

        def run(params, grads, state, *maybe_w):
            local = jax.tree.map(lambda x: x[0], (params, grads, state))
            p, g, s = local
            kw = {"weights": maybe_w[0]} if maybe_w else {}
            new_p, new_s = inner(p, g, s, **kw)
            return jax.tree.map(lambda x: x[None], (new_p, new_s))

        n_w = 1 if with_weights else 0
        # Donate grads + state only: XLA aliases the grads buffer (same
        # tree shape) into new_params, which is the whole params-sized
        # saving; donating params too would just trigger "unusable donated
        # buffer" warnings since no same-shaped output remains to alias.
        return jax.jit(jax.shard_map(
            run, mesh=mesh,
            in_specs=(spec, spec, spec) + (P(),) * n_w,
            out_specs=(spec, spec)),
            donate_argnums=(1, 2) if self.donate else ())

    def _hier_gossip_bundle(self, ctx) -> dict:
        """Compiled two-level bundle for the ``hierarchical_gossip``
        communication type (BLUEFOG_TPU_HIER) — also stashes the modeled
        per-level wire metadata ``step()`` feeds into
        ``bf_comm_level_bytes_total``."""
        from bluefog_tpu.utils import config
        cfg = config.get()
        if not cfg.hier:
            raise RuntimeError(
                "CommunicationType.hierarchical_gossip requires "
                "BLUEFOG_TPU_HIER=1 (default off — the flat path stays "
                "bit-identical without it)")
        if ctx.local_size >= len(ctx.devices):
            raise RuntimeError(
                "hierarchical_gossip needs a multi-slice mesh: call "
                "bf.init(local_size=<ranks per slice>) so "
                "machine_size() > 1")
        ht = basics._hier_topology(ctx, cfg)
        (inner_sched, outer_scheds, inner_edges), _sig = \
            basics._hier_bundle(ctx, ht, cfg)
        comp = cfg.hier_outer_compression
        frac = (config.parse_sparse_frac(comp)
                if comp.startswith("sparse") else None)
        self._hier_meta = (ht, inner_edges, comp, frac)
        return {"inner_sched": inner_sched, "outer_scheds": outer_scheds,
                "outer_every": ht.outer_every, "outer_compression": comp,
                "outer_frac": frac}

    def _step_callable(self, with_weights: bool, plan=None):
        ctx = basics._require_init()
        key = (ctx.topology_version, ctx.machine_topology_version,
               with_weights,
               None if plan is None else plan.signature)
        if key not in self._jitted:
            self._jitted[key] = self._build_step(with_weights, plan)
        return self._jitted[key]

    # -- public surface -----------------------------------------------------
    def init(self, params) -> DistOptState:
        """Build rank-major optimizer state for rank-major ``params``."""
        ctx = basics._require_init()
        hier = (self.communication_type in (
                CommunicationType.hierarchical_neighbor_allreduce,
                CommunicationType.hierarchical_gossip))
        mesh = ctx.hier_mesh if hier else ctx.mesh
        spec = P((MACHINE_AXIS, LOCAL_AXIS)) if hier else P(RANK_AXIS)

        def run(params):
            local = jax.tree.map(lambda x: x[0], params)
            st = F.dist_init(self.base, local)
            return jax.tree.map(lambda x: x[None], st)
        placed = jax.tree.map(basics._place, params)
        return jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(spec,), out_specs=spec))(placed)

    def step(self, params, grads, state: DistOptState, *,
             self_weight: Optional[float] = None,
             src_weights=None, dst_weights=None):
        """One optimizer step; returns ``(new_params, new_state)``.

        Weight kwargs override the schedule's weights for this step only
        (traced — no recompilation when they change every iteration).
        """
        import time as _time

        from bluefog_tpu.utils import profiler, telemetry
        t0 = telemetry.start_timer()
        w = basics._weight_override_matrix(self_weight, src_weights, dst_weights)
        plan = self._shard_plan(params)
        placed = jax.tree.map(basics._place, (params, grads))
        params, grads = placed
        fn = self._step_callable(with_weights=w is not None, plan=plan)
        if w is None:
            out = basics._throttle(fn(params, grads, state))
        else:
            out = basics._throttle(
                fn(params, grads, state, jnp.asarray(w, jnp.float32)))
        hier_meta = getattr(self, "_hier_meta", None)
        if hier_meta is not None:
            # Per-level wire accounting of the fused two-level step (the
            # compiled program never crosses Python per level).  The step
            # index must mirror the traced state.step the combiner's
            # cadence cond reads — on a checkpoint resume that does NOT
            # start at zero, so the base is read off the first step's
            # state once (one host sync, first call only) and advanced
            # host-side from there.
            if self._hier_step0 is None:
                # state.step is rank-major (one identical counter per
                # rank row); any row is the value.
                self._hier_step0 = int(
                    np.asarray(state.step).reshape(-1)[0])
            t = self._hier_step0 + self._steps_seen
            if t % self.num_steps_per_communication == 0:
                ht, inner_edges, comp, _frac = hier_meta
                tree_bytes = float(sum(
                    x.nbytes for x in jax.tree_util.tree_leaves(params)))
                basics._record_hier_levels(ht, t, tree_bytes,
                                           inner_edges, comp)
        if plan is not None and telemetry.enabled():
            # Per-shard wire accounting, same cadence machinery as the
            # hier path above (the fused program never crosses Python, so
            # the comm-step condition is reconstructed host-side).
            from bluefog_tpu.ops import sharded as SH
            if self._shard_step0 is None:
                self._shard_step0 = int(
                    np.asarray(state.step).reshape(-1)[0])
            t = self._shard_step0 + self._steps_seen
            if t % self.num_steps_per_communication == 0:
                rep_ici, rep_dcn, grp_edges = \
                    self._shard_telemetry_meta(plan)
                SH.record_level_bytes(
                    plan, rep_ici_edges=rep_ici, rep_dcn_edges=rep_dcn,
                    grp_edges=grp_edges, compression=self.compression)
        self._steps_seen += 1
        # DISPATCH wall time (async — device work keeps running); the
        # synced profile below measures true step latency.
        telemetry.observe_since(t0, "bf_optimizer_step_seconds",
                                family="collective")
        pe = profiler.profile_period(self.profile_every)
        if pe and self._steps_seen % pe == 0 and t0 is not None:
            # Synced sample: the step is one fused XLA program, so phase
            # attribution inside it is impossible — what this measures is
            # the whole step's true wall time (dispatch-to-done, including
            # device work queued ahead of it) plus the straggler gather.
            t_sync = _time.perf_counter()
            jax.block_until_ready(out)
            now = _time.perf_counter()
            outer = profiler.active()
            if outer is not None:
                # An enclosing bf.step_profile() owns this step's record:
                # credit the sync wait to it and let ITS exit record the
                # (now truly synced) step and gather stragglers — once,
                # not twice.
                outer.attribute("host-sync", now - t_sync)
                outer.request_straggler()
            else:
                profiler.record_synced_step(
                    now - t0, phases={"optimizer-update": t_sync - t0,
                                      "host-sync": now - t_sync})
        # costs_communication: this sampler adds a combine + host sync,
        # so it only runs when the consensus period was explicitly set.
        k = telemetry.consensus_every(costs_communication=True)
        if k and self._steps_seen % k == 0:
            _sample_consensus_distance(out[0])
        return out


def _sample_consensus_distance(params) -> None:
    """Record the consensus-distance gauge: per rank,
    ``||x_r - (W^T x)_r||_2`` over the flattened parameter tree, where
    ``W^T x`` is the weighted neighborhood mean the ACTIVE topology's
    gossip pulls toward — the per-step disagreement the scaling-efficiency
    claim rests on.  Rides the eager ``neighbor_allreduce`` path (so it is
    exact in multi-process runs) and costs one extra combine of the
    parameters every K steps; mean/max over ranks land in
    ``bf_consensus_distance`` / ``bf_consensus_distance_max``."""
    from bluefog_tpu.utils import telemetry
    n = basics.size()
    leaves = [jnp.reshape(jnp.asarray(x), (n, -1)).astype(jnp.float32)
              for x in jax.tree_util.tree_leaves(params)]
    if not leaves:
        return
    flat = jnp.concatenate(leaves, axis=1)
    mean = basics.neighbor_allreduce(flat)
    dist = np.asarray(basics.to_numpy(
        jnp.linalg.norm(flat - mean, axis=1)))
    telemetry.record_consensus_distance(float(dist.mean()),
                                        float(dist.max()))


# ---------------------------------------------------------------------------
# Parity factories (reference torch/optimizers.py:1180-1554)
# ---------------------------------------------------------------------------

def DistributedGradientAllreduceOptimizer(
        base, *, num_steps_per_communication: int = 1,
        **kw) -> DistributedOptimizer:
    """Horovod-equivalent synchronous gradient averaging
    (reference ``:1376``)."""
    return DistributedOptimizer(
        base, CommunicationType.allreduce, order="gradient_allreduce",
        num_steps_per_communication=num_steps_per_communication, **kw)


def DistributedAllreduceOptimizer(
        base, *, num_steps_per_communication: int = 1,
        **kw) -> DistributedOptimizer:
    """Synchronous parameter consensus via global averaging
    (reference ``:1301``)."""
    return DistributedOptimizer(
        base, CommunicationType.allreduce, order="awc",
        num_steps_per_communication=num_steps_per_communication, **kw)


def DistributedNeighborAllreduceOptimizer(
        base, *, num_steps_per_communication: int = 1,
        use_dynamic_topology: bool = False, phases=None,
        **kw) -> DistributedOptimizer:
    """The flagship: AWC neighbor averaging over the active topology
    (reference ``:1326``)."""
    return DistributedOptimizer(
        base, CommunicationType.neighbor_allreduce, order="awc",
        num_steps_per_communication=num_steps_per_communication,
        use_dynamic_topology=use_dynamic_topology, phases=phases, **kw)


def DistributedHierarchicalNeighborAllreduceOptimizer(
        base, *, num_steps_per_communication: int = 1,
        use_dynamic_topology: bool = False, phases=None,
        **kw) -> DistributedOptimizer:
    """Machine-level neighbor averaging: local ICI allreduce fused with
    machine-graph exchange (reference ``:1352``)."""
    return DistributedOptimizer(
        base, CommunicationType.hierarchical_neighbor_allreduce, order="awc",
        num_steps_per_communication=num_steps_per_communication,
        use_dynamic_topology=use_dynamic_topology, phases=phases, **kw)


def DistributedHierarchicalGossipOptimizer(
        base, *, num_steps_per_communication: int = 1,
        order: str = "awc", **kw) -> DistributedOptimizer:
    """Two-level hierarchical gossip (``BLUEFOG_TPU_HIER``): dense
    intra-slice neighbor averaging over ICI every step, sparse one-peer
    inter-slice exchange over DCN on its own cadence with its own
    compression (``BLUEFOG_TPU_HIER_OUTER_*``) — the pod-scale
    composition of ROADMAP item 2 (HiCCL line), fused into the jitted
    step like every collective-family order."""
    return DistributedOptimizer(
        base, CommunicationType.hierarchical_gossip, order=order,
        num_steps_per_communication=num_steps_per_communication, **kw)


def DistributedAdaptWithCombineOptimizer(
        base, communication_type=CommunicationType.neighbor_allreduce,
        *, num_steps_per_communication: int = 1,
        use_dynamic_topology: bool = False, phases=None,
        **kw) -> DistributedOptimizer:
    """AWC with a chosen communication type (reference ``:1497``)."""
    return DistributedOptimizer(
        base, communication_type, order="awc",
        num_steps_per_communication=num_steps_per_communication,
        use_dynamic_topology=use_dynamic_topology, phases=phases, **kw)


def DistributedAdaptThenCombineOptimizer(
        base, communication_type=CommunicationType.neighbor_allreduce,
        *, num_steps_per_communication: int = 1,
        use_dynamic_topology: bool = False, phases=None,
        **kw) -> DistributedOptimizer:
    """ATC with a chosen communication type (reference ``:1426``)."""
    return DistributedOptimizer(
        base, communication_type, order="atc",
        num_steps_per_communication=num_steps_per_communication,
        use_dynamic_topology=use_dynamic_topology, phases=phases, **kw)
