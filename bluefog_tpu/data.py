"""Input pipeline: rank-partitioned sampling + host-async device prefetch.

The reference delegates data loading to torch (``DistributedSampler`` +
``DataLoader``, ``examples/pytorch_mnist.py:100-120``); a standalone TPU
framework needs its own feed.  Two pieces:

* :class:`DistributedSampler` — epoch-seeded global permutation partitioned
  across ranks, same contract as the torch sampler the reference's examples
  use (``set_epoch`` reshuffles; ``drop_last`` keeps shards equal — SPMD
  requires identical shapes on every rank anyway).
* :func:`prefetch_to_device` / :class:`ShardedLoader` — a background thread
  assembles the next batches and ``jax.device_put``\\ s them with the
  rank-major sharding while the current step computes, hiding host→HBM
  transfer behind the MXU.  (flax's ``jax_utils.prefetch_to_device`` is
  pmap-era and GPU-gated; this one targets ``NamedSharding`` over the rank
  mesh and works on any backend.)

Batches are **rank-major**: leading dim ``bf.size()``, row ``r`` is rank
``r``'s per-device batch — the same convention as every eager op
(``docs/ops.md``).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np

__all__ = ["DistributedSampler", "ShardedLoader", "prefetch_to_device"]


class DistributedSampler:
    """Partition ``num_samples`` indices across ranks with per-epoch shuffles.

    Parity: ``torch.utils.data.distributed.DistributedSampler`` as used by
    the reference's examples (``pytorch_mnist.py:100-104``) — but this one
    yields the index matrix for ALL ranks at once (rank-major row ``r`` =
    rank ``r``'s indices), matching the single-controller data model.
    """

    def __init__(self, num_samples: int, *, num_ranks: Optional[int] = None,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = True, static_shards: bool = False):
        if num_ranks is None:
            from bluefog_tpu import basics
            num_ranks = basics.size()
        if num_samples < num_ranks:
            raise ValueError(
                f"cannot shard {num_samples} samples over {num_ranks} ranks")
        self.num_samples = num_samples
        self.num_ranks = num_ranks
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        # static_shards pins shard membership: rank r always owns the r-th
        # contiguous block, and per-epoch shuffling happens *within* shards.
        # This is the heterogeneous-data decentralized-DP setting; the torch
        # sampler (and static_shards=False) re-partitions globally each
        # epoch, which makes rank data IID over time.
        self.static_shards = static_shards
        self.epoch = 0
        self.per_rank = num_samples // num_ranks
        if not drop_last and num_samples % num_ranks:
            # pad by wrapping (torch sampler semantics: repeat early samples)
            self.per_rank += 1

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shuffle (call once per epoch, every process — the
        permutation must be identical everywhere, like the torch sampler's
        ``seed + epoch`` contract)."""
        self.epoch = int(epoch)

    def indices(self) -> np.ndarray:
        """``(num_ranks, per_rank)`` int array; row ``r`` = rank ``r``."""
        total = self.per_rank * self.num_ranks
        if self.static_shards:
            perm = np.arange(self.num_samples)
            if total > perm.size:
                perm = np.concatenate([perm, perm[:total - perm.size]])
            shards = perm[:total].reshape(self.num_ranks, self.per_rank)
            if self.shuffle:
                rng = np.random.RandomState(self.seed + self.epoch)
                for r in range(self.num_ranks):  # shuffle within shard only
                    shards[r] = shards[r][rng.permutation(self.per_rank)]
            return shards
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            perm = rng.permutation(self.num_samples)
        else:
            perm = np.arange(self.num_samples)
        if total > perm.size:  # wrap-pad (drop_last=False)
            perm = np.concatenate([perm, perm[:total - perm.size]])
        return perm[:total].reshape(self.num_ranks, self.per_rank)

    def __iter__(self) -> Iterator[np.ndarray]:
        """Yield ``(num_ranks,)`` index columns one sample position at a
        time (rarely what you want — prefer :class:`ShardedLoader`)."""
        return iter(self.indices().T)

    def __len__(self) -> int:
        return self.per_rank


def prefetch_to_device(it: Iterable, *, size: int = 2,
                       sharding=None) -> Iterator:
    """Wrap a host iterator of (pytrees of) numpy batches: a daemon thread
    stays ``size`` batches ahead, placing each on device so the consumer
    never blocks on host→HBM transfer.

    ``sharding=None`` uses the framework's rank-major sharding (leading dim
    partitioned over the rank mesh); pass any ``jax.sharding.Sharding`` to
    override, or ``False`` to skip placement (raw numpy out).
    """
    if sharding is None:
        from bluefog_tpu import basics
        sharding = basics._rank_sharding()

    q: "queue.Queue" = queue.Queue(maxsize=max(1, size))
    _END = object()
    stop = threading.Event()  # consumer abandoned: let the producer exit

    def place(batch):
        if sharding is False:
            return batch
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)

    def offer(item) -> bool:
        """Put unless the consumer went away; never blocks indefinitely."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in it:
                if not offer(place(batch)):
                    return
        except Exception as e:  # surface in the consumer, not the thread
            offer(e)
            return
        offer(_END)

    threading.Thread(target=producer, daemon=True,
                     name="bf-data-prefetch").start()

    def consumer():
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            # Early break / error in the training loop: release the producer
            # (it may be blocked in a pre-stop put) and drop staged batches.
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    return consumer()


class ShardedLoader:
    """Batched, shuffled, prefetched feed over in-memory arrays.

    ``arrays`` is a pytree of numpy arrays with matching leading dimension
    (the sample axis).  Each yielded batch is the pytree with leaves of
    shape ``(num_ranks, batch_size, ...)`` placed on device with the
    rank-major sharding — drop-in for the training loops in ``examples/``.

    ``transform`` (optional) maps the raw numpy batch before device
    placement (augmentation, dtype casts) and runs on the prefetch thread,
    off the critical path.
    """

    def __init__(self, arrays, batch_size: int, *,
                 num_ranks: Optional[int] = None, shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True,
                 static_shards: bool = False,
                 transform: Optional[Callable] = None,
                 prefetch: int = 2, sharding=None):
        leaves = jax.tree.leaves(arrays)
        if not leaves:
            raise ValueError("empty dataset")
        n = leaves[0].shape[0]
        for leaf in leaves:
            if leaf.shape[0] != n:
                raise ValueError("all leaves need the same sample axis; got "
                                 f"{leaf.shape[0]} vs {n}")
        self.arrays = arrays
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.sampler = DistributedSampler(
            n, num_ranks=num_ranks, shuffle=shuffle, seed=seed,
            drop_last=drop_last, static_shards=static_shards)
        self.transform = transform
        self.prefetch = prefetch
        # None = rank-major framework sharding; False = raw numpy (host-side
        # loaders, or num_ranks != bf.size()); any Sharding = explicit.
        self.sharding = sharding
        if drop_last and self.sampler.per_rank < batch_size:
            raise ValueError(
                f"per-rank shard ({self.sampler.per_rank}) smaller than "
                f"batch_size ({batch_size})")

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)

    @property
    def steps_per_epoch(self) -> int:
        if self.drop_last:
            return self.sampler.per_rank // self.batch_size
        # drop_last=False: wrap-pad the batch axis too, so the tail trains —
        # SPMD needs static shapes, so a short final batch is not an option.
        return -(-self.sampler.per_rank // self.batch_size)

    def _batches(self) -> Iterator:
        idx = self.sampler.indices()  # (ranks, per_rank)
        need = self.steps_per_epoch * self.batch_size
        if need > idx.shape[1]:  # drop_last=False tail: wrap within shards
            idx = np.concatenate([idx, idx[:, :need - idx.shape[1]]], axis=1)
        for s in range(self.steps_per_epoch):
            take = idx[:, s * self.batch_size:(s + 1) * self.batch_size]
            batch = jax.tree.map(lambda a: a[take], self.arrays)
            if self.transform is not None:
                batch = self.transform(batch)
            yield batch

    def __iter__(self) -> Iterator:
        return prefetch_to_device(self._batches(), size=self.prefetch,
                                  sharding=self.sharding)

    def __len__(self) -> int:
        return self.steps_per_epoch
