"""Chaos harness: kill a rank mid-gossip, watch the survivors re-form.

    python -m bluefog_tpu.tools chaos [--np 4] [--steps 360] \
        [--kill-rank 3] [--kill-step 40] [--smoke]

Launches a CPU multi-process gang under ``bfrun --chaos`` running a small
decentralized-optimization workload over the one-sided window path (each
rank descends toward its own target and neighbor-averages through
``win_put`` / ``win_update``), SIGKILLs one rank mid-run, and asserts the
churn controller's whole promise end to end:

  * the survivors reach failure consensus and commit a new membership
    epoch WITHOUT a global restart (``bf_membership_changes_total``,
    ``/healthz`` "membership" block);
  * gossip re-plans onto a survivor-only topology (``set_topology``
    re-entered live; windows rebuilt from owned rows) within a bounded
    number of steps of the kill;
  * the run converges to the survivor-consensus optimum (the mean of the
    surviving ranks' targets — the same fixed point an uninterrupted
    survivor-only run reaches);
  * post-recovery step time stays within 1.5x the pre-failure median.

Why this workload shape: the gang rides ONLY the DCN window transport
(TCP) for gossip and membership — the exact paths that keep working when
the gang is broken.  No jax collective is ever issued across processes,
so the harness runs on stock CPU containers where multi-process XLA
computations are unavailable, and the jax coordinator is used purely for
rendezvous (with wide heartbeat windows, so the coordination service
never pre-empts the churn controller's own failure handling).

``--worker`` is the internal per-rank entry point ``bfrun`` launches; the
driver is what operators (and ``make chaos-smoke``) run.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

__all__ = ["main"]

_RESULT_TAG = "CHAOS_RESULT "


# ---------------------------------------------------------------------------
# Worker (one gang rank)
# ---------------------------------------------------------------------------

def _init_rendezvous() -> None:
    """jax.distributed init with very wide heartbeat windows: the churn
    controller owns failure handling; the coordination service must not
    terminate survivors just because a peer died (its default does)."""
    coord = os.environ.get("BFTPU_COORDINATOR")
    if coord is None:
        raise SystemExit("chaos --worker must be launched under bfrun")
    kwargs = dict(
        coordinator_address=coord,
        num_processes=int(os.environ["BFTPU_NUM_PROCESSES"]),
        process_id=int(os.environ["BFTPU_PROCESS_ID"]))
    try:
        from jax._src import distributed as _dist
        _dist.global_state.initialize(
            service_heartbeat_interval_seconds=10,
            service_max_missing_heartbeats=100000,
            client_heartbeat_interval_seconds=10,
            client_max_missing_heartbeats=100000, **kwargs)
    except TypeError:
        # Heartbeat kwargs moved/renamed on this jax: plain init still
        # works as long as the run outlives the default windows.
        import jax
        jax.distributed.initialize(**kwargs)


def _median_ms(samples) -> float:
    return float(statistics.median(samples)) * 1e3 if samples else 0.0


def _done_barrier(active_procs, my_proc: int, grace: float) -> None:
    """Two-phase exit ordering over the coordinator's KV store (pure gRPC
    — no collective).  Load-bearing for the gang's shutdown order: the
    jax coordinator lives inside proc 0, and ANY survivor still holding a
    live coordination client when proc 0 exits gets hard-aborted through
    the coordination service's error poll — a fake casualty the harness
    would misread as churn.  Phase 1: everyone announces its loop is done
    and waits for the other ACTIVE survivors (dead procs are exactly the
    ones that cannot answer, so they are never waited on).  Phase 2:
    non-coordinator procs announce exit and leave immediately; proc 0
    waits for those announcements and leaves LAST."""
    try:
        from jax._src import distributed as _dist
        client = _dist.global_state.client
        others = [p for p in sorted(active_procs) if p != my_proc]
        client.key_value_set(f"bf/chaos_done/{my_proc}", "1")
        for p in others:
            client.blocking_key_value_get(f"bf/chaos_done/{p}", 60_000)
        if my_proc != 0:
            client.key_value_set(f"bf/chaos_exit/{my_proc}", "1")
            return
        for p in others:
            client.blocking_key_value_get(f"bf/chaos_exit/{p}", 30_000)
    except Exception as e:  # noqa: BLE001 — degrade to a plain grace sleep
        print(f"chaos worker: done-barrier degraded to sleep ({e})",
              file=sys.stderr, flush=True)
        time.sleep(grace)


def worker_main(args) -> int:
    os.environ.setdefault("BLUEFOG_TPU_TELEMETRY", "1")
    _init_rendezvous()
    import jax
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.run.supervisor import ChurnSupervisor
    from bluefog_tpu.utils import config, telemetry
    config.reload()
    bf.init()
    W.init_transport()
    me = bf.rank()
    target = float(me)
    x = np.full(args.dim, target, np.float32)
    name = "chaos_x"
    W.win_create(x[None].copy(), name, zero_init=True)
    sup = ChurnSupervisor()
    port = telemetry.start_http_server(0)

    times = []
    recovery_step = None
    view = None
    put_errors = 0
    seen_srcs = set()  # in-neighbors that have ever contributed gossip
    for step in range(args.steps):
        t0 = time.perf_counter()
        change = sup.step(step)
        if change is not None:
            view = change
            if change.evicted:
                break
            recovery_step = step
            seen_srcs.clear()  # fresh window, fresh staging
        # Local descent toward this rank's own target...
        x = x - args.lr * (x - target)
        # ...then asynchronous neighbor averaging: push my iterate to the
        # out-neighbors, combine whatever my in-neighbors have delivered so
        # far (combine-what-you-have: a neighbor whose put has not landed
        # yet simply sits this round out — no waiting, no barrier).
        try:
            W.win_put(x[None], name)
        except ConnectionError:
            put_errors += 1  # a dead peer not yet voted out
        seen_srcs.update(
            s for s, v in W.get_win_version(name, me).items() if v > 0)
        if seen_srcs:
            w = 1.0 / (len(seen_srcs) + 1)
            out = W.win_update(name, self_weight=w,
                               neighbor_weights={s: w for s in seen_srcs})
            x = np.asarray(out)[0].astype(np.float32)
        times.append(time.perf_counter() - t0)
        if args.pace_ms:
            time.sleep(args.pace_ms / 1e3)

    info = sup.info()
    # Scrape our own /healthz over HTTP — the operator-facing surface the
    # smoke must prove, not just the in-process dict.
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            hz = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:  # 503 when degraded — still JSON
        hz = json.loads(e.read().decode())
    snap = telemetry.snapshot()
    # Pre-failure baseline: the steady window right BEFORE the kill, not
    # the whole prefix — the first dozens of steps are warm-up (drain
    # threads idle, heartbeats not yet flowing) and would understate the
    # baseline the 1.5x regression bound is judged against.
    pre = times[max(2, args.kill_step - 60):args.kill_step] \
        if args.kill_step < len(times) else times[2:]
    post = (times[recovery_step + 2:]
            if recovery_step is not None else [])
    print(_RESULT_TAG + json.dumps({
        "rank": me,
        "proc": jax.process_index(),
        "epoch": info["epoch"],
        "active_ranks": info["active_ranks"],
        "changes_total": info["changes_total"],
        "evicted": bool(view.evicted if view is not None else False),
        "steps": len(times),
        "recovery_step": recovery_step,
        "x_mean": float(x.mean()),
        "put_errors": put_errors,
        "pre_median_ms": round(_median_ms(pre), 3),
        "post_median_ms": round(_median_ms(post), 3),
        # Per-50-step medians: the raw trend, so a failed regression bound
        # can be told apart from ambient host-load noise at a glance.
        "seg_ms": [round(_median_ms(times[i:i + 50]), 2)
                   for i in range(0, len(times), 50)],
        "recovery_observed":
            snap.get("bf_churn_recovery_seconds_count", 0) >= 1,
        "healthz_membership": hz.get("membership"),
    }), flush=True)
    # Exit in lockstep: heartbeats keep running while slower survivors
    # finish (finish-time skew must not read as churn), and proc 0 — the
    # jax coordinator's host — must leave LAST.
    evicted = bool(view is not None and view.evicted)
    active_procs = set() if evicted else {
        W._store.distrib.rank_owner[r] for r in info["active_ranks"]}
    sys.stdout.flush()
    sys.stderr.flush()
    _done_barrier(active_procs, jax.process_index(), args.grace)
    # os._exit, not sys.exit: the jax distributed client's exit-time
    # shutdown barrier would block on the chaos-killed task forever, and a
    # non-coordinator survivor must leave with NOTHING between its exit
    # announcement and the exit itself (see _done_barrier).
    os._exit(0)


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def _fail(msgs, what):
    msgs.append(what)


def run_demo(args) -> int:
    n = args.np
    if args.spec:
        # The assertions below are kill-shaped (survivor set, recovery
        # bound anchored on the kill step): a --spec override must carry
        # exactly one kill so the harness judges against the right gang.
        # Other fault mixes run under `bfrun --chaos` directly.
        from bluefog_tpu.utils.chaos import killed_ranks, parse_chaos
        kills = killed_ranks(parse_chaos(args.spec))
        if len(kills) != 1:
            raise SystemExit(
                "chaos: --spec must contain exactly one kill fault "
                f"(got {kills}); drive delay/partition-only mixes with "
                "`bfrun --chaos` directly")
        kill_rank = kills[0]
        args.kill_step = next(f.step for f in parse_chaos(args.spec)
                              if f.kind == "kill")
        spec = args.spec
    else:
        kill_rank = (n - 1) if args.kill_rank is None else args.kill_rank
        spec = f"kill:rank={kill_rank}:step={args.kill_step}"
    if kill_rank == 0:
        # The jax rendezvous coordinator lives inside rank 0: its death is
        # a whole-gang loss (every coordination client hard-aborts), not a
        # gossip-churn event.  Production deployments pin the coordinator
        # outside the gang; this harness just refuses the footgun.
        raise SystemExit("chaos: rank 0 hosts the rendezvous coordinator "
                         "and cannot be the kill target — pick any other "
                         "rank")
    survivors = sorted(set(range(n)) - {kill_rank})
    cmd = [sys.executable, "-m", "bluefog_tpu.run", "-np", str(n),
           "--devices-per-proc", "1", "--chaos", spec, "--",
           sys.executable, "-m", "bluefog_tpu.tools", "chaos", "--worker",
           "--steps", str(args.steps), "--dim", str(args.dim),
           "--lr", str(args.lr), "--pace-ms", str(args.pace_ms),
           "--grace", str(args.grace), "--kill-step", str(args.kill_step)]
    import tempfile
    rec_dir = tempfile.mkdtemp(prefix="bf-chaos-flightrec-")
    rec_prefix = os.path.join(rec_dir, "flightrec")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BLUEFOG_TPU_CHURN": "1",
        "BLUEFOG_TPU_CHURN_HEARTBEAT_MS": "80",
        "BLUEFOG_TPU_CHURN_SUSPECT_MS": "500",
        "BLUEFOG_TPU_WIN_RETRIES": "1",
        "BLUEFOG_TPU_WIN_RETRY_BACKOFF_MS": "25",
        "BLUEFOG_TPU_TELEMETRY": "1",
        # Black-box leg: recorder armed + sampled wire trace tags, so the
        # committed membership change makes every survivor dump a
        # postmortem the driver can merge (the CI path for reading the
        # flight recorder after a kill — not just unit tests).
        "BLUEFOG_TPU_FLIGHT_RECORDER": "1",
        "BLUEFOG_TPU_TRACE_SAMPLE": "4",
        "BLUEFOG_TPU_FLIGHT_RECORDER_PATH": rec_prefix,
    })
    print(f"chaos: launching {n}-process gang, {spec} "
          f"({args.steps} steps)...", flush=True)
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=args.timeout)
    wall = time.perf_counter() - t0
    results = {}
    for line in proc.stdout.splitlines():
        if line.startswith(_RESULT_TAG):
            # bfrun multiplexes the gang's stdout; another process's line
            # can land on the same physical line without a newline in
            # between.  The record is one JSON object — parse exactly it
            # and ignore any interleaved trailing bytes (observed flaky
            # in CI as "Extra data" JSONDecodeError).
            rec, _end = json.JSONDecoder().raw_decode(
                line[len(_RESULT_TAG):])
            results[rec["rank"]] = rec

    failures = []
    if proc.returncode != 0:
        _fail(failures, f"bfrun exited {proc.returncode} (the chaos kill "
                        "must be tolerated, any other failure is real)")
    if sorted(results) != survivors:
        _fail(failures, f"expected reports from survivors {survivors}, "
                        f"got {sorted(results)}")
    target_mean = sum(float(r) for r in survivors) / len(survivors)
    for rank in sorted(results):
        r = results[rank]
        line = (f"  rank {rank}: epoch {r['epoch']}, active "
                f"{r['active_ranks']}, x_mean {r['x_mean']:.4f} "
                f"(target {target_mean:.4f}), recovery@{r['recovery_step']}"
                f", step ms pre/post {r['pre_median_ms']:.2f}/"
                f"{r['post_median_ms']:.2f}, put_errors {r['put_errors']}")
        print(line, flush=True)
        if r["epoch"] < 1:
            _fail(failures, f"rank {rank}: no membership epoch committed")
        if list(r["active_ranks"]) != survivors:
            _fail(failures, f"rank {rank}: active ranks {r['active_ranks']}"
                            f" != survivors {survivors}")
        if r["recovery_step"] is None:
            _fail(failures, f"rank {rank}: never recovered")
        elif r["recovery_step"] - args.kill_step > args.recovery_bound:
            _fail(failures,
                  f"rank {rank}: recovery took "
                  f"{r['recovery_step'] - args.kill_step} steps "
                  f"(bound {args.recovery_bound})")
        if not r["recovery_observed"]:
            _fail(failures, f"rank {rank}: bf_churn_recovery_seconds "
                            "histogram never observed")
        m = r.get("healthz_membership")
        if not m or m.get("epoch", 0) < 1:
            _fail(failures, f"rank {rank}: /healthz carries no committed "
                            f"membership block ({m})")
        if abs(r["x_mean"] - target_mean) > args.loss_tol:
            _fail(failures,
                  f"rank {rank}: consensus value {r['x_mean']:.4f} is "
                  f"{abs(r['x_mean'] - target_mean):.4f} from the "
                  f"survivor optimum {target_mean:.4f} "
                  f"(tol {args.loss_tol})")
        # Step-time regression: medians floored at pace + 5 ms — on a
        # small shared CI box the op time is a few ms and ambient load
        # swings it by more than that, so an anomalously QUIET pre-window
        # must not fabricate a regression a genuinely slow post-recovery
        # path (tens of ms: leftover retries, a peer not dropped) would
        # still trip.
        floor = args.pace_ms + 5.0
        pre = max(r["pre_median_ms"], floor)
        post = max(r["post_median_ms"], floor)
        if post / pre > args.step_ratio:
            _fail(failures, f"rank {rank}: post-recovery step time "
                            f"{post:.2f}ms > {args.step_ratio}x "
                            f"pre-failure {pre:.2f}ms")
    # Flight-recorder postmortem: every survivor dumps its black box at
    # the committed membership change (run/supervisor.py); the dumps must
    # decode into one valid merged trace — the exact artifact an operator
    # reads after a real kill.
    try:
        from bluefog_tpu.tools import tracegossip
        rec_files = tracegossip.dump_files(rec_prefix)
        missing = [r for r in survivors if r not in rec_files]
        if missing:
            _fail(failures, "no flight-recorder dump from survivor(s) "
                            f"{missing} (found {sorted(rec_files)})")
        else:
            dumps = tracegossip.load_dumps(rec_prefix)
            out, stats = tracegossip.merge_gossip(rec_prefix, dumps=dumps)
            with open(out) as f:
                merged = json.load(f)
            lanes = {e.get("pid") for e in merged}
            if not set(survivors) <= lanes:
                _fail(failures, f"merged trace lanes {sorted(lanes)} miss "
                                f"survivors {survivors}")
            print(f"chaos: flight-recorder postmortem OK — "
                  f"{stats['events']} events from ranks {stats['ranks']}, "
                  f"{stats['flows_matched']} cross-rank flow arrow(s)",
                  flush=True)
    except Exception as e:  # noqa: BLE001 — a broken dump IS the failure
        _fail(failures, f"flight-recorder postmortem failed: {e}")
    finally:
        import shutil
        shutil.rmtree(rec_dir, ignore_errors=True)
    if failures:
        print("\nchaos FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        tail = "\n".join(proc.stderr.splitlines()[-40:])
        print(f"\ngang stderr tail:\n{tail}", file=sys.stderr)
        return 1
    print(f"chaos OK: rank {kill_rank} killed at step {args.kill_step}, "
          f"{len(survivors)} survivors re-formed and converged to "
          f"{target_mean:.3f} (wall {wall:.1f}s)", flush=True)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.tools chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--worker", action="store_true",
                   help="internal: run as one gang rank (launched by the "
                        "driver through bfrun)")
    p.add_argument("--np", type=int, default=4,
                   help="gang size (default 4)")
    p.add_argument("--steps", type=int, default=360,
                   help="training steps per rank (default 360)")
    p.add_argument("--dim", type=int, default=128,
                   help="parameter-vector length (default 128)")
    p.add_argument("--lr", type=float, default=0.2)
    p.add_argument("--pace-ms", type=float, default=5.0,
                   help="per-step pacing sleep (stabilizes step-time "
                        "medians on loaded hosts)")
    p.add_argument("--grace", type=float, default=3.0,
                   help="post-loop heartbeat grace before exiting, so "
                        "finish-time skew never reads as churn")
    p.add_argument("--kill-rank", type=int, default=None,
                   help="rank to SIGKILL (default: the last one)")
    p.add_argument("--kill-step", type=int, default=120,
                   help="step at which the kill fires (late enough that "
                        "the pre-failure baseline is measured in steady "
                        "state, past the warm-up)")
    p.add_argument("--spec", default=None,
                   help="full chaos spec override (bfrun --chaos grammar); "
                        "default kill:rank=<kill-rank>:step=<kill-step>")
    p.add_argument("--recovery-bound", type=int, default=250,
                   help="max steps between the kill and the survivors' "
                        "re-plan (default 250)")
    p.add_argument("--loss-tol", type=float, default=0.15,
                   help="|consensus - survivor target mean| bound")
    p.add_argument("--step-ratio", type=float, default=1.5,
                   help="post/pre step-time median bound")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--smoke", action="store_true",
                   help="CI smoke profile (same assertions, smaller run)")
    args = p.parse_args(argv)
    if args.worker:
        return worker_main(args)
    if args.smoke:
        args.steps = min(args.steps, 300)
        args.dim = min(args.dim, 64)
    return run_demo(args)


if __name__ == "__main__":
    sys.exit(main())
