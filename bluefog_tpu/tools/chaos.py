"""Chaos harness: kill a rank mid-gossip, watch the survivors re-form.

    python -m bluefog_tpu.tools chaos [--np 4] [--steps 360] \
        [--kill-rank 3] [--kill-step 40] [--smoke]

Delay scenario (``--delay-smoke`` / ``--delay``): the same gang with a
``delay:`` fault instead of a kill, run TWICE — synchronous gossip (a
step barrier every step: the lockstep coupling the window family had
before async mode) and barrier-free async gossip (``BLUEFOG_TPU_ASYNC=1``
push-sum accumulates, bounded-staleness fold, exact-collect backstop).
Asserts the tentpole's operational claim end to end:

  * sync mode DEGRADES toward the slowest rank: the survivors' step time
    during the fault rises to the delayed rank's cadence;
  * async mode holds survivor step throughput at the no-fault baseline
    (bounded ratio) — a straggler costs its contributions' freshness,
    not the fleet's throughput;
  * the delayed rank is NOT evicted when it is merely slow, even with
    ``BLUEFOG_TPU_CHURN_STRAGGLER_STEPS`` armed (the staleness policy,
    not membership, absorbs it — the widened async step-lag bound);
  * both modes reach the same consensus optimum (matched final loss):
    push-sum mass conservation holds through rejection + the backstop.

The step barrier rides the jax coordinator's KV store (pure gRPC), like
the exit barrier — no jax collective is ever issued across processes, so
the harness runs on stock CPU containers.

Link-observatory scenario (``--links-smoke`` / ``--links``): the async
gang again, but judged on the LINK OBSERVATORY instead of throughput — a
``linkdelay:`` fault holds one rank's outbound DATA links at +60 ms and
the harness asserts the affected edges' online delay EWMAs converge on
the injected delay while unaffected edges stay flat, measured-vs-modeled
divergence crosses the alert threshold, exactly the matching
``BLUEFOG_TPU_SLO`` rule fires on the receiver ranks (breach counter +
degraded ``/healthz`` links block + one flight-recorder dump) while a
co-armed quiet rule stays silent, every rank computes the identical
merged link matrix, and ``tools top`` renders one complete frame against
the live gang's real ``/metrics`` endpoints.  ``make links-smoke``.

Self-tuning control-plane scenario (``--tune-smoke`` / ``--tune``): the
same async gang started on a DELIBERATELY wrong topology for the coming
fault — a full mesh, so a ``linkdelay:`` fault (which sleeps the sender
once per outbound DATA message) taxes the delayed rank once per peer per
step.  Run TWICE: with ``BLUEFOG_TPU_TUNE=1`` the tuner must measure the
hot edges, commit EXACTLY ONE numbered adaptation epoch that re-routes
onto a cheap topology and recover >= 2x of the lost gossip throughput
without a restart (``/healthz`` "tuner" block, ``tools top`` tune
column); with ``BLUEFOG_TPU_TUNE=0`` pinned, the same fault must leave
the schedule bitwise unchanged and register ZERO ``bf_tune_*`` series —
the default-off contract.  ``make tune-smoke``.

Launches a CPU multi-process gang under ``bfrun --chaos`` running a small
decentralized-optimization workload over the one-sided window path (each
rank descends toward its own target and neighbor-averages through
``win_put`` / ``win_update``), SIGKILLs one rank mid-run, and asserts the
churn controller's whole promise end to end:

  * the survivors reach failure consensus and commit a new membership
    epoch WITHOUT a global restart (``bf_membership_changes_total``,
    ``/healthz`` "membership" block);
  * gossip re-plans onto a survivor-only topology (``set_topology``
    re-entered live; windows rebuilt from owned rows) within a bounded
    number of steps of the kill;
  * the run converges to the survivor-consensus optimum (the mean of the
    surviving ranks' targets — the same fixed point an uninterrupted
    survivor-only run reaches);
  * post-recovery step time stays within 1.5x the pre-failure median.

Why this workload shape: the gang rides ONLY the DCN window transport
(TCP) for gossip and membership — the exact paths that keep working when
the gang is broken.  No jax collective is ever issued across processes,
so the harness runs on stock CPU containers where multi-process XLA
computations are unavailable, and the jax coordinator is used purely for
rendezvous (with wide heartbeat windows, so the coordination service
never pre-empts the churn controller's own failure handling).

``--worker`` is the internal per-rank entry point ``bfrun`` launches; the
driver is what operators (and ``make chaos-smoke``) run.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

__all__ = ["main"]

_RESULT_TAG = "CHAOS_RESULT "


# ---------------------------------------------------------------------------
# Worker (one gang rank)
# ---------------------------------------------------------------------------

def _init_rendezvous() -> None:
    """jax.distributed init with very wide heartbeat windows: the churn
    controller owns failure handling; the coordination service must not
    terminate survivors just because a peer died (its default does)."""
    coord = os.environ.get("BFTPU_COORDINATOR")
    if coord is None:
        raise SystemExit("chaos --worker must be launched under bfrun")
    kwargs = dict(
        coordinator_address=coord,
        num_processes=int(os.environ["BFTPU_NUM_PROCESSES"]),
        process_id=int(os.environ["BFTPU_PROCESS_ID"]))
    try:
        from jax._src import distributed as _dist
        _dist.global_state.initialize(
            service_heartbeat_interval_seconds=10,
            service_max_missing_heartbeats=100000,
            client_heartbeat_interval_seconds=10,
            client_max_missing_heartbeats=100000, **kwargs)
    except TypeError:
        # Heartbeat kwargs moved/renamed on this jax: plain init still
        # works as long as the run outlives the default windows.
        import jax
        jax.distributed.initialize(**kwargs)


def _median_ms(samples) -> float:
    return float(statistics.median(samples)) * 1e3 if samples else 0.0


def _robust_window_ms(samples, parts: int = 3) -> float:
    """Load-robust step-time statistic (ms): the MIN over the window's
    sub-window medians.  A transient host-load burst on a shared CI box
    inflates at most one sub-window's median, so the min tracks the
    window's true uncontended cadence — while a STRUCTURAL slowdown (the
    sync leg's lockstep coupling, a genuinely delayed rank) inflates
    every sub-window and still shows at full size.  A single whole-window
    median was the delay leg's flake: one load lull or burst on either
    side of the ratio tipped the 3.0x / 1.5x bounds."""
    if not samples:
        return 0.0
    k = max(1, len(samples) // parts)
    meds = [statistics.median(samples[i:i + k])
            for i in range(0, len(samples), k)]
    return float(min(meds)) * 1e3


def _done_barrier(active_procs, my_proc: int, grace: float) -> None:
    """Two-phase exit ordering over the coordinator's KV store (pure gRPC
    — no collective).  Load-bearing for the gang's shutdown order: the
    jax coordinator lives inside proc 0, and ANY survivor still holding a
    live coordination client when proc 0 exits gets hard-aborted through
    the coordination service's error poll — a fake casualty the harness
    would misread as churn.  Phase 1: everyone announces its loop is done
    and waits for the other ACTIVE survivors (dead procs are exactly the
    ones that cannot answer, so they are never waited on).  Phase 2:
    non-coordinator procs announce exit and leave immediately; proc 0
    waits for those announcements and leaves LAST."""
    try:
        from jax._src import distributed as _dist
        client = _dist.global_state.client
        others = [p for p in sorted(active_procs) if p != my_proc]
        client.key_value_set(f"bf/chaos_done/{my_proc}", "1")
        for p in others:
            client.blocking_key_value_get(f"bf/chaos_done/{p}", 60_000)
        if my_proc != 0:
            client.key_value_set(f"bf/chaos_exit/{my_proc}", "1")
            return
        for p in others:
            client.blocking_key_value_get(f"bf/chaos_exit/{p}", 30_000)
    except Exception as e:  # noqa: BLE001 — degrade to a plain grace sleep
        print(f"chaos worker: done-barrier degraded to sleep ({e})",
              file=sys.stderr, flush=True)
        time.sleep(grace)


def worker_main(args) -> int:
    os.environ.setdefault("BLUEFOG_TPU_TELEMETRY", "1")
    _init_rendezvous()
    import jax
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.run.supervisor import ChurnSupervisor
    from bluefog_tpu.utils import config, telemetry
    config.reload()
    bf.init()
    W.init_transport()
    me = bf.rank()
    target = float(me)
    x = np.full(args.dim, target, np.float32)
    name = "chaos_x"
    W.win_create(x[None].copy(), name, zero_init=True)
    sup = ChurnSupervisor()
    port = telemetry.start_http_server(0)

    times = []
    recovery_step = None
    view = None
    put_errors = 0
    seen_srcs = set()  # in-neighbors that have ever contributed gossip
    for step in range(args.steps):
        t0 = time.perf_counter()
        change = sup.step(step)
        if change is not None:
            view = change
            if change.evicted:
                break
            recovery_step = step
            seen_srcs.clear()  # fresh window, fresh staging
        # Local descent toward this rank's own target...
        x = x - args.lr * (x - target)
        # ...then asynchronous neighbor averaging: push my iterate to the
        # out-neighbors, combine whatever my in-neighbors have delivered so
        # far (combine-what-you-have: a neighbor whose put has not landed
        # yet simply sits this round out — no waiting, no barrier).
        try:
            W.win_put(x[None], name)
        except ConnectionError:
            put_errors += 1  # a dead peer not yet voted out
        seen_srcs.update(
            s for s, v in W.get_win_version(name, me).items() if v > 0)
        if seen_srcs:
            w = 1.0 / (len(seen_srcs) + 1)
            out = W.win_update(name, self_weight=w,
                               neighbor_weights={s: w for s in seen_srcs})
            x = np.asarray(out)[0].astype(np.float32)
        times.append(time.perf_counter() - t0)
        if args.pace_ms:
            time.sleep(args.pace_ms / 1e3)

    info = sup.info()
    # Scrape our own /healthz over HTTP — the operator-facing surface the
    # smoke must prove, not just the in-process dict.
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            hz = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:  # 503 when degraded — still JSON
        hz = json.loads(e.read().decode())
    snap = telemetry.snapshot()
    # Pre-failure baseline: the steady window right BEFORE the kill, not
    # the whole prefix — the first dozens of steps are warm-up (drain
    # threads idle, heartbeats not yet flowing) and would understate the
    # baseline the 1.5x regression bound is judged against.
    pre = times[max(2, args.kill_step - 60):args.kill_step] \
        if args.kill_step < len(times) else times[2:]
    post = (times[recovery_step + 2:]
            if recovery_step is not None else [])
    print(_RESULT_TAG + json.dumps({
        "rank": me,
        "proc": jax.process_index(),
        "epoch": info["epoch"],
        "active_ranks": info["active_ranks"],
        "changes_total": info["changes_total"],
        "evicted": bool(view.evicted if view is not None else False),
        "steps": len(times),
        "recovery_step": recovery_step,
        "x_mean": float(x.mean()),
        "put_errors": put_errors,
        "pre_median_ms": round(_median_ms(pre), 3),
        "post_median_ms": round(_median_ms(post), 3),
        # Per-50-step medians: the raw trend, so a failed regression bound
        # can be told apart from ambient host-load noise at a glance.
        "seg_ms": [round(_median_ms(times[i:i + 50]), 2)
                   for i in range(0, len(times), 50)],
        "recovery_observed":
            snap.get("bf_churn_recovery_seconds_count", 0) >= 1,
        "healthz_membership": hz.get("membership"),
    }), flush=True)
    # Exit in lockstep: heartbeats keep running while slower survivors
    # finish (finish-time skew must not read as churn), and proc 0 — the
    # jax coordinator's host — must leave LAST.
    evicted = bool(view is not None and view.evicted)
    active_procs = set() if evicted else {
        W._store.distrib.rank_owner[r] for r in info["active_ranks"]}
    sys.stdout.flush()
    sys.stderr.flush()
    _done_barrier(active_procs, jax.process_index(), args.grace)
    # os._exit, not sys.exit: the jax distributed client's exit-time
    # shutdown barrier would block on the chaos-killed task forever, and a
    # non-coordinator survivor must leave with NOTHING between its exit
    # announcement and the exit itself (see _done_barrier).
    os._exit(0)


def _parse_results(stdout: str) -> dict:
    """Collect every CHAOS_RESULT record from the gang's multiplexed
    stdout.  bfrun interleaves the processes' output: several records can
    land on ONE physical line (no newline in between) and a record can
    carry trailing bytes from another stream — split on the tag itself
    and raw_decode exactly one JSON object per fragment."""
    results = {}
    for line in stdout.splitlines():
        parts = line.split(_RESULT_TAG)
        for frag in parts[1:]:
            try:
                rec, _end = json.JSONDecoder().raw_decode(frag)
            except json.JSONDecodeError:
                continue  # torn record (process died mid-write)
            results[rec["rank"]] = rec
    return results


# ---------------------------------------------------------------------------
# Elastic gang workers (coordinator-free bootstrap + mid-run join)
# ---------------------------------------------------------------------------
# The join/kill0 legs run the SAME decentralized-optimization workload as
# the kill leg, but the gang bootstraps through ops/gang.py's replicated
# endpoint directory instead of the jax coordinator: no jax.distributed
# init at all, so killing rank 0's host removes one gossip peer, not the
# rendezvous service.  A fresh process joins mid-run (`bfrun --join
# @<prefix>`), is granted the vacant rank(s) placement-aware, and the gang
# commits exactly one grow epoch — convergence then targets the FULL-gang
# optimum again.


def _gossip_loop(args, sup, W, name, me, x, steps, step0=0,
                 deadline=None):
    """The shared descend + win_put + combine-what-you-have loop; returns
    (x, times, recovery_step, last_view, put_errors, epochs).

    ``deadline`` (unix seconds) aligns loop ENDS across the gang: the
    founding members and a late-admitted joiner start at different wall
    times, but everyone must stop gossiping together — a member that
    keeps descending against a joiner's frozen last value would drift
    off the consensus optimum the assertions check."""
    import numpy as np
    times = []
    recovery_step = None
    view = None
    put_errors = 0
    epochs = []
    target = float(me)
    seen_srcs = set()
    for step in range(step0, step0 + steps):
        if deadline is not None and time.time() >= deadline:
            break
        t0 = time.perf_counter()
        change = sup.step(step)
        if change is not None:
            view = change
            epochs.append(change.epoch)
            if change.evicted:
                break
            recovery_step = step
            seen_srcs.clear()  # fresh window, fresh staging
        x = x - args.lr * (x - target)
        try:
            W.win_put(x[None], name)
        except ConnectionError:
            put_errors += 1  # a dead peer not yet voted out
        seen_srcs.update(
            s for s, v in W.get_win_version(name, me).items() if v > 0)
        if seen_srcs:
            w = 1.0 / (len(seen_srcs) + 1)
            out = W.win_update(name, self_weight=w,
                               neighbor_weights={s: w for s in seen_srcs})
            x = np.asarray(out)[0].astype(np.float32)
        times.append(time.perf_counter() - t0)
        if args.pace_ms:
            time.sleep(args.pace_ms / 1e3)
    return x, times, recovery_step, view, put_errors, epochs


def _elastic_report(role, me, proc, sup, x, extra):
    """One CHAOS_RESULT record for the elastic legs (shared shape between
    founding members and the joiner)."""
    import bluefog_tpu as bf
    info = sup.info()
    rec = {
        "role": role,
        "rank": me,
        "proc": proc,
        "epoch": info["epoch"],
        "active_ranks": info["active_ranks"],
        "changes_total": info["changes_total"],
        "x_mean": float(x.mean()),
        "gang": bf.gang_info(),
    }
    rec.update(extra)
    print(_RESULT_TAG + json.dumps(rec), flush=True)


def elastic_worker_main(args) -> int:
    """One FOUNDING member of a coordinator-free gang: bootstraps from the
    pre-assigned endpoint list (``bfrun --elastic``), never touches
    jax.distributed, serves join grants, and survives any peer's death —
    rank 0's included."""
    os.environ.setdefault("BLUEFOG_TPU_TELEMETRY", "1")
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu.ops import gang
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.run.supervisor import ChurnSupervisor
    from bluefog_tpu.utils import config
    config.reload()
    bf.init()
    gang.init_elastic()
    d = W._store.distrib
    me = d.my_rank
    x = np.full(args.dim, float(me), np.float32)
    name = "gang_x"
    W.win_create(x[None].copy(), name, zero_init=True)
    sup = ChurnSupervisor()
    x, times, recovery_step, view, put_errors, epochs = _gossip_loop(
        args, sup, W, name, me, x, args.steps, deadline=args.deadline)
    evicted = bool(view is not None and view.evicted)
    pre = times[max(2, args.kill_step - 60):args.kill_step] \
        if args.kill_step < len(times) else times[2:]
    post = (times[recovery_step + 2:]
            if recovery_step is not None else [])
    _elastic_report("member", me, d.my_proc, sup, x, {
        "evicted": evicted,
        "steps": len(times),
        "recovery_step": recovery_step,
        "epochs": epochs,
        "put_errors": put_errors,
        "pre_median_ms": round(_median_ms(pre), 3),
        "post_median_ms": round(_median_ms(post), 3),
    })
    sys.stdout.flush()
    sys.stderr.flush()
    # No coordinator, no exit barrier needed: keep heartbeating (and
    # serving gossip) through the grace window so slower finishers — the
    # late-admitted joiner above all — converge before we disappear.
    time.sleep(args.grace)
    os._exit(0)


def join_worker_main(args) -> int:
    """The JOINING process: contacts any live member through the persisted
    directory (``BFTPU_GANG_JOIN=@<prefix>``), waits for the grow epoch to
    commit, creates its windows from the granted owned-row snapshot, and
    gossips as a full member from then on."""
    os.environ.setdefault("BLUEFOG_TPU_TELEMETRY", "1")
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu.ops import gang
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.run.supervisor import ChurnSupervisor
    from bluefog_tpu.utils import config
    config.reload()
    bf.init()
    target_spec = os.environ.get("BFTPU_GANG_JOIN")
    if not target_spec:
        raise SystemExit("chaos --role joiner needs BFTPU_GANG_JOIN "
                         "(launch through `bfrun --join`)")
    grant = gang.join_gang(target_spec)
    sup = ChurnSupervisor()
    admitted_after = None
    t0 = time.monotonic()
    step = 0
    view = None
    while time.monotonic() - t0 < args.join_wait:
        change = sup.step(step)
        if change is not None:
            view = change
        step += 1
        if not sup.ctrl.joining:
            admitted_after = round(time.monotonic() - t0, 3)
            break
        time.sleep(0.05)
    me = min(grant.ranks)
    if admitted_after is None:
        _elastic_report("joiner", me, grant.proc, sup,
                        np.zeros(1, np.float32),
                        {"admitted": False, "steps": 0})
        sys.stdout.flush()
        os._exit(1)
    # The grow epoch is committed and the survivor topology re-planned
    # (sup.step ran the growth recovery): materialize the windows from
    # the grant's owned-row snapshot — a survivor's consensus estimate —
    # and gossip as an ordinary member.  Peers' puts that raced ahead of
    # win_create were parked and replay in arrival order.
    name = "gang_x"
    w = grant.windows.get(name)
    if w is None:
        rows = np.zeros((len(grant.ranks), args.dim), np.float32)
    else:
        rows = np.stack([np.asarray(w["rows"][r], dtype=w["dtype"])
                         for r in sorted(grant.ranks)])
    W.win_create(rows.copy(), name, zero_init=True)
    x = rows[0].astype(np.float32).copy()
    print(f"chaos joiner: entering gossip loop at {time.time():.3f} "
          f"(deadline {args.deadline}, steps cap {args.steps}, "
          f"step0 {step})", file=sys.stderr, flush=True)
    x2, times, _rec, view, put_errors, epochs = _gossip_loop(
        args, sup, W, name, me, x, args.steps, step0=step,
        deadline=args.deadline)
    _elastic_report("joiner", me, grant.proc, sup, x2, {
        "admitted": True,
        "admitted_after_sec": admitted_after,
        "grant_epoch": grant.epoch,
        "granted_ranks": list(grant.ranks),
        "evicted": bool(view is not None and view.evicted),
        "steps": len(times),
        "epochs": epochs,
        "put_errors": put_errors,
    })
    sys.stdout.flush()
    sys.stderr.flush()
    time.sleep(min(args.grace, 2.0))
    os._exit(0)


def run_elastic_demo(args, kill_rank: int) -> int:
    """Driver for the join and kill-rank-0 legs: launch a coordinator-free
    gang under ``bfrun --elastic --chaos kill:...``, wait for the shrink
    epoch to land in the persisted directory, then admit a replacement
    through ``bfrun --join @<prefix>`` and judge the whole promise:

      * the gang survives the kill (rank 0's included — no coordinator);
      * the directory serves the joiner's bootstrap from disk;
      * exactly ONE grow epoch commits (epoch 2: shrink then grow);
      * every member — the joiner included — converges to the FULL-gang
        optimum (matched final loss vs a never-shrunk run).
    """
    import tempfile

    from bluefog_tpu.ops.gang import GangDirectory
    n = args.np
    spec = f"kill:rank={kill_rank}:step={args.kill_step}"
    survivors = sorted(set(range(n)) - {kill_rank})
    tmpdir = tempfile.mkdtemp(prefix="bf-gang-demo-")
    prefix = os.path.join(tmpdir, "gang")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BLUEFOG_TPU_CHURN": "1",
        "BLUEFOG_TPU_ELASTIC_JOIN": "1",
        "BLUEFOG_TPU_CHURN_HEARTBEAT_MS": "80",
        "BLUEFOG_TPU_CHURN_SUSPECT_MS": "500",
        "BLUEFOG_TPU_WIN_RETRIES": "1",
        "BLUEFOG_TPU_WIN_RETRY_BACKOFF_MS": "25",
        "BLUEFOG_TPU_TELEMETRY": "1",
    })
    # Everyone — founding members and the late joiner — stops gossiping
    # at one shared wall-clock deadline, so the final iterates are a
    # joint consensus snapshot, not a race against exit skew.
    deadline = time.time() + args.run_sec
    cmd = [sys.executable, "-m", "bluefog_tpu.run", "-np", str(n),
           "--devices-per-proc", "1", "--elastic", "--gang-dir", prefix,
           "--chaos", spec, "--",
           sys.executable, "-m", "bluefog_tpu.tools", "chaos", "--worker",
           "--role", "member", "--steps", str(args.steps),
           "--dim", str(args.dim), "--lr", str(args.lr),
           "--pace-ms", str(args.pace_ms), "--grace", str(args.grace),
           "--kill-step", str(args.kill_step),
           "--deadline", repr(deadline)]
    leg = "kill-rank-0" if kill_rank == 0 else "join"
    print(f"chaos {leg}: launching {n}-process coordinator-free gang, "
          f"{spec} ({args.steps} steps, directory @{prefix})...",
          flush=True)
    t_start = time.perf_counter()
    # Output to FILES, not pipes: the driver must keep polling the
    # directory while the gang runs, and four ranks' stderr would fill a
    # pipe long before the run ends.
    gang_out = open(os.path.join(tmpdir, "gang.out"), "w+")
    gang_err = open(os.path.join(tmpdir, "gang.err"), "w+")
    gang_proc = subprocess.Popen(cmd, env=env, stdout=gang_out,
                                 stderr=gang_err, text=True)
    failures = []
    join_results = {}
    join_stderr = ""
    try:
        # Phase 1: the kill lands and the survivors commit the shrink
        # epoch — observable from OUTSIDE through the persisted replicas.
        poll_deadline = time.monotonic() + args.timeout / 2
        shrunk = False
        while time.monotonic() < poll_deadline:
            if gang_proc.poll() is not None:
                break
            try:
                merged = GangDirectory.load_any(prefix)
                if merged.epoch >= 1 and merged.vacant_ranks():
                    shrunk = True
                    break
            except (FileNotFoundError, OSError):
                pass
            time.sleep(0.2)
        if not shrunk:
            _fail(failures, "the persisted gang directory never reached a "
                            "committed shrink epoch with a vacant rank")
        else:
            # Phase 2: admit a replacement through the directory — the
            # exact bootstrap path an operator's replacement pod takes.
            join_cmd = [sys.executable, "-m", "bluefog_tpu.run", "-np",
                        "1", "--devices-per-proc", str(n),
                        "--join", f"@{prefix}", "--gang-dir", prefix,
                        "--",
                        sys.executable, "-m", "bluefog_tpu.tools",
                        "chaos", "--worker", "--role", "joiner",
                        "--steps", str(args.steps),
                        "--dim", str(args.dim), "--lr", str(args.lr),
                        "--pace-ms", str(args.pace_ms),
                        "--grace", str(args.grace),
                        "--join-wait", str(args.join_wait),
                        "--deadline", repr(deadline)]
            join_proc = subprocess.run(
                join_cmd, env=env, capture_output=True, text=True,
                timeout=args.timeout / 2)
            join_results = _parse_results(join_proc.stdout)
            join_stderr = join_proc.stderr
            if join_proc.returncode != 0:
                _fail(failures,
                      f"join bfrun exited {join_proc.returncode}")
        rc = gang_proc.wait(timeout=args.timeout)
        if rc != 0:
            _fail(failures, f"gang bfrun exited {rc} (the chaos kill must "
                            "be tolerated, any other failure is real)")
    finally:
        if gang_proc.poll() is None:
            gang_proc.kill()
            gang_proc.wait(timeout=30)
        gang_out.seek(0)
        gang_stdout = gang_out.read()
        gang_err.seek(0)
        gang_stderr = gang_err.read()
        gang_out.close()
        gang_err.close()
    wall = time.perf_counter() - t_start
    results = _parse_results(gang_stdout)
    members = {r: v for r, v in results.items()
               if v.get("role") == "member"}
    joiners = [v for v in join_results.values()
               if v.get("role") == "joiner"]
    if sorted(members) != survivors:
        _fail(failures, f"expected member reports from survivors "
                        f"{survivors}, got {sorted(members)}")
    if not joiners:
        _fail(failures, "no report from the joining process")
    # Full-gang optimum: the joiner revives the killed rank's seat (and
    # its target), so the network optimum is the NEVER-SHRUNK mean.
    target_mean = sum(range(n)) / n
    reports = ([(f"rank {r} (member)", v) for r, v in sorted(
        members.items())]
        + [(f"rank {v.get('rank')} (joiner)", v) for v in joiners])
    for label, r in reports:
        line = (f"  {label}: epoch {r['epoch']}, active "
                f"{r['active_ranks']}, x_mean {r['x_mean']:.4f} "
                f"(target {target_mean:.4f}), changes "
                f"{r['changes_total']}")
        if r.get("admitted_after_sec") is not None:
            line += f", admitted after {r['admitted_after_sec']}s"
        line += f", {r.get('steps', '?')} steps"
        print(line, flush=True)
        if r.get("evicted"):
            _fail(failures, f"{label}: evicted")
        # Exactly one shrink + exactly one grow epoch, gang-wide (the
        # joiner entered at the shrink epoch, so it sees one commit).
        want_changes = 2 if r.get("role") == "member" else 1
        if r["epoch"] != 2 or r["changes_total"] != want_changes:
            _fail(failures,
                  f"{label}: expected exactly one shrink + one grow "
                  f"epoch (epoch 2, {want_changes} change(s)), got epoch "
                  f"{r['epoch']} with {r['changes_total']} changes")
        if sorted(r["active_ranks"]) != list(range(n)):
            _fail(failures,
                  f"{label}: final active ranks {r['active_ranks']} != "
                  f"the full gang {list(range(n))}")
        if abs(r["x_mean"] - target_mean) > args.loss_tol:
            _fail(failures,
                  f"{label}: consensus {r['x_mean']:.4f} is "
                  f"{abs(r['x_mean'] - target_mean):.4f} from the "
                  f"full-gang optimum {target_mean:.4f} "
                  f"(tol {args.loss_tol})")
    for v in joiners:
        if not v.get("admitted"):
            _fail(failures, "the joiner was never admitted (no grow "
                            "epoch committed)")
        elif sorted(v.get("granted_ranks", [])) != [kill_rank]:
            _fail(failures,
                  f"joiner was granted {v.get('granted_ranks')}, expected "
                  f"the vacant rank [{kill_rank}]")
    if failures:
        print(f"\nchaos {leg} FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        tail = "\n".join(gang_stderr.splitlines()[-40:])
        print(f"\ngang stderr tail:\n{tail}", file=sys.stderr)
        jtail = "\n".join(join_stderr.splitlines()[-25:])
        if jtail:
            print(f"\njoiner stderr tail:\n{jtail}", file=sys.stderr)
        return 1
    import shutil
    shutil.rmtree(tmpdir, ignore_errors=True)
    print(f"chaos {leg} OK: rank {kill_rank} killed at step "
          f"{args.kill_step}, survivors committed the shrink, a fresh "
          f"process bootstrapped from the directory, took rank "
          f"{kill_rank} via one grow epoch, and the gang converged to "
          f"the full-gang optimum {target_mean:.3f} (wall {wall:.1f}s)",
          flush=True)
    return 0


# ---------------------------------------------------------------------------
# Delay-scenario worker (sync vs async gossip under a straggler fault)
# ---------------------------------------------------------------------------

def _kv_barrier(tag: str, my_proc: int, n: int,
                timeout_ms: int = 180_000) -> None:
    """All-process rendezvous over the coordinator's KV store (pure gRPC
    — the chaos gangs never issue a jax collective).  ``tag`` must be
    unique per barrier instance (KV keys are write-once)."""
    from jax._src import distributed as _dist
    client = _dist.global_state.client
    client.key_value_set(f"bf/sbar/{tag}/{my_proc}", "1")
    for p in range(n):
        if p != my_proc:
            client.blocking_key_value_get(f"bf/sbar/{tag}/{p}", timeout_ms)


def delay_worker_main(args) -> int:
    """One rank of the delay-scenario gang: scalar push-sum consensus
    over ``win_accumulate`` / ``win_update_then_collect`` (owned layout,
    associated-P on), descending toward this rank's own target — the
    network optimum is the mean of the targets, so the final de-biased
    value is the matched-loss oracle both modes must reach.

    ``--mode sync``: a KV step barrier EVERY step — lockstep gossip, the
    whole gang steps at the slowest rank's cadence.  ``--mode async``:
    no per-step barrier; the only rendezvous is the exact-collect
    backstop every ``BLUEFOG_TPU_ASYNC_COLLECT_EVERY`` steps (flush +
    barrier + stale-residual fold), mirroring the optimizer family's
    backstop."""
    os.environ.setdefault("BLUEFOG_TPU_TELEMETRY", "1")
    _init_rendezvous()
    import jax
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.run.supervisor import ChurnSupervisor
    from bluefog_tpu.utils import config
    config.reload()
    bf.init()
    W.init_transport()      # arms BLUEFOG_TPU_ASYNC from config
    me = bf.rank()
    n = bf.size()
    nproc = jax.process_count()
    my_proc = jax.process_index()
    W.turn_on_win_ops_with_associated_p()
    target = float(me)
    x = np.zeros(args.dim, np.float32) + target
    name = "delay_x"
    W.win_create(np.zeros((1, args.dim), np.float32), name, zero_init=True)
    # Seed the window's exposed memory with my starting value (P = 1).
    win = W._store.get(name)
    with win.lock:
        win.main[me][:] = x
    sup = ChurnSupervisor()
    outs = sorted(bf.out_neighbor_ranks(me))
    share = 1.0 / (len(outs) + 1.0)
    dst_w = {o: share for o in outs}
    every = config.get().async_collect_every if args.mode == "async" else 0

    def settle(tag):
        """Flush + rendezvous + drain-settle + residual fold: the chaos
        gang's stand-in for win_fence (whose trailing barrier is a jax
        collective these gangs cannot issue)."""
        W.win_flush()
        _kv_barrier(tag, my_proc, nproc)
        time.sleep(0.05)    # peers' blocking sends are on TCP; let the
        _kv_barrier(tag + "b", my_proc, nproc)  # drain threads apply
        W.win_fold_stale_residuals(name)

    from bluefog_tpu.utils import telemetry
    times = []
    view = None
    for step in range(args.steps):
        t0 = time.perf_counter()
        change = sup.step(step)
        if change is not None:
            view = change
            if change.evicted:
                break
        if args.mode == "async":
            # Publish the step clock (what the optimizer family's
            # _async_step_begin does): trace tags carry it as the origin
            # step, so receivers age this rank's gossip exactly.
            W.set_async_step(step)
            telemetry.set_gauge("bf_async_step_lag",
                                float(W.async_step_lag()), rank=str(me))
        # Subgradient-push: descend the numerator at the de-biased point,
        # then one column-stochastic accumulate round + collect.
        p = max(W.win_associated_p(name, me), 1e-3)
        z = x / p
        x = x - args.lr * (z - target) * p
        W.win_accumulate(x[None], name, self_weight=share,
                         dst_weights=dst_w)
        if args.mode == "sync":
            _kv_barrier(f"s{step}", my_proc, nproc)
        elif every and (step + 1) % every == 0:
            settle(f"c{step}")
        x = np.asarray(W.win_update_then_collect(name))[0]
        times.append(time.perf_counter() - t0)
        if args.pace_ms:
            time.sleep(args.pace_ms / 1e3)

    evicted = bool(view is not None and view.evicted)
    info = sup.info()
    if not evicted:
        # Final exact collect: after the settle nothing is in flight and
        # every policy-held residual is folded, so the de-biased value is
        # the exact conserved estimate (the matched-loss oracle).
        settle("final")
        x = np.asarray(W.win_update_then_collect(name))[0]
    z = x / max(W.win_associated_p(name, me), 1e-3)
    snap = telemetry.snapshot()
    stale = {k: v for k, v in snap.items()
             if k.startswith("bf_win_stale_")}
    lo, hi = args.fault_step, args.fault_step + args.fault_steps
    pre = times[max(2, lo - 40):lo]
    fault = times[lo:hi]
    # Min-of-sub-medians, not one whole-window median: both sides get the
    # same load-burst filtering, so the sync/async ratio bounds judge the
    # structural coupling, not ambient CI noise (see _robust_window_ms).
    pre_ms = _robust_window_ms(pre)
    fault_ms = _robust_window_ms(fault)
    print(_RESULT_TAG + json.dumps({
        "rank": me,
        "proc": my_proc,
        "mode": args.mode,
        "epoch": info["epoch"],
        "changes_total": info["changes_total"],
        "active_ranks": info["active_ranks"],
        "evicted": evicted,
        "steps": len(times),
        "z_mean": float(z.mean()),
        "pre_median_ms": round(pre_ms, 3),
        "fault_median_ms": round(fault_ms, 3),
        "stale_counters": stale,
        "async_step_lag": snap.get(f'bf_async_step_lag{{rank="{me}"}}'),
    }), flush=True)
    active_procs = set() if evicted else set(range(nproc))
    sys.stdout.flush()
    sys.stderr.flush()
    _done_barrier(active_procs, my_proc, args.grace)
    os._exit(0)


def run_delay_demo(args) -> int:
    """Launch the delay gang twice — sync then async — and judge the
    tentpole's operational claims (see the worker docstring)."""
    n = args.np
    delay_rank = (n - 1) if args.delay_rank is None else args.delay_rank
    if delay_rank == 0:
        raise SystemExit("chaos: rank 0 hosts the rendezvous coordinator; "
                         "delay any other rank")
    spec = (f"delay:rank={delay_rank}:step={args.fault_step}"
            f":steps={args.fault_steps}:ms={args.delay_ms}")
    target_mean = sum(range(n)) / n

    def leg(mode):
        cmd = [sys.executable, "-m", "bluefog_tpu.run", "-np", str(n),
               "--devices-per-proc", "1", "--chaos", spec, "--",
               sys.executable, "-m", "bluefog_tpu.tools", "chaos",
               "--worker", "--mode", mode,
               "--steps", str(args.steps), "--dim", str(args.dim),
               "--lr", str(args.lr), "--pace-ms", str(args.pace_ms),
               "--grace", str(args.grace),
               "--fault-step", str(args.fault_step),
               "--fault-steps", str(args.fault_steps)]
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "BLUEFOG_TPU_CHURN": "1",
            "BLUEFOG_TPU_CHURN_HEARTBEAT_MS": "80",
            "BLUEFOG_TPU_CHURN_SUSPECT_MS": "800",
            "BLUEFOG_TPU_TELEMETRY": "1",
            # Step-lag eviction ARMED: the async leg must prove a
            # merely-slow rank survives it (the widened bound).
            "BLUEFOG_TPU_CHURN_STRAGGLER_STEPS": "10",
            "BLUEFOG_TPU_TRACE_SAMPLE": "2",
        })
        if mode == "async":
            env.update({
                "BLUEFOG_TPU_ASYNC": "1",
                "BLUEFOG_TPU_ASYNC_STALENESS_STEPS": "8",
                "BLUEFOG_TPU_ASYNC_STALENESS_POLICY": "reject",
                "BLUEFOG_TPU_ASYNC_COLLECT_EVERY":
                    str(args.collect_every),
            })
        else:
            env.pop("BLUEFOG_TPU_ASYNC", None)
        print(f"chaos delay: launching {n}-process {mode} gang, {spec} "
              f"({args.steps} steps)...", flush=True)
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=args.timeout)
        return proc, _parse_results(proc.stdout)

    failures = []
    t0 = time.perf_counter()
    legs = {mode: leg(mode) for mode in ("sync", "async")}
    wall = time.perf_counter() - t0
    floor = args.pace_ms + 2.0
    survivor_ratio = {}
    for mode, (proc, results) in legs.items():
        if proc.returncode != 0:
            _fail(failures, f"{mode}: bfrun exited {proc.returncode}")
            print(f"\n{mode} gang stderr tail:\n"
                  + "\n".join(proc.stderr.splitlines()[-30:]),
                  file=sys.stderr)
            continue
        if sorted(results) != list(range(n)):
            _fail(failures, f"{mode}: expected reports from all {n} ranks,"
                            f" got {sorted(results)}")
            continue
        ratios = []
        for rank, r in sorted(results.items()):
            line = (f"  [{mode}] rank {rank}: step ms pre/fault "
                    f"{r['pre_median_ms']:.2f}/{r['fault_median_ms']:.2f},"
                    f" z_mean {r['z_mean']:.4f} (target {target_mean:.4f})"
                    f", epoch {r['epoch']}"
                    + (f", lag {r['async_step_lag']}"
                       if r.get("async_step_lag") is not None else ""))
            print(line, flush=True)
            # Matched final loss: both modes reach the consensus optimum.
            if abs(r["z_mean"] - target_mean) > args.loss_tol:
                _fail(failures,
                      f"{mode} rank {rank}: consensus {r['z_mean']:.4f} "
                      f"is {abs(r['z_mean'] - target_mean):.4f} from the "
                      f"optimum {target_mean:.4f} (tol {args.loss_tol})")
            # The merely-slow rank must never be voted out — in EITHER
            # mode (async proves the widened step-lag bound).
            if r["evicted"] or r["epoch"] != 0 or r["changes_total"] != 0:
                _fail(failures,
                      f"{mode} rank {rank}: membership changed (epoch "
                      f"{r['epoch']}, changes {r['changes_total']}, "
                      f"evicted {r['evicted']}) — a merely-slow rank was "
                      "treated as churn")
            if rank != delay_rank:
                pre = max(r["pre_median_ms"], floor)
                ratios.append(max(r["fault_median_ms"], floor) / pre)
        if ratios:
            survivor_ratio[mode] = max(ratios)
    if "sync" in survivor_ratio and "async" in survivor_ratio:
        sr, ar = survivor_ratio["sync"], survivor_ratio["async"]
        print(f"chaos delay: survivor fault/pre step-time ratio — "
              f"sync {sr:.2f}x vs async {ar:.2f}x "
              f"(delay {args.delay_ms}ms, pace {args.pace_ms}ms)",
              flush=True)
        # Sync gossip degrades toward the slowest rank's cadence...
        if sr < args.sync_degrade:
            _fail(failures,
                  f"sync survivors did not degrade (ratio {sr:.2f}x < "
                  f"{args.sync_degrade}x) — the lockstep leg is not "
                  "measuring the coupling")
        # ...while async survivors hold the no-fault baseline.
        if ar > args.async_ratio:
            _fail(failures,
                  f"async survivors degraded {ar:.2f}x > bound "
                  f"{args.async_ratio}x — barrier-free gossip is not "
                  "holding throughput under the straggler")
        async_results = legs["async"][1]
        if not any(r.get("stale_counters")
                   for r in async_results.values()):
            _fail(failures,
                  "async leg never exercised the staleness policy (no "
                  "bf_win_stale_* counters ticked) — the bound/delay "
                  "parameters are not producing stale contributions")
    if failures:
        print("\nchaos delay FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"chaos delay OK: rank {delay_rank} delayed "
          f"{args.delay_ms}ms x {args.fault_steps} steps — sync degraded "
          f"{survivor_ratio['sync']:.2f}x, async held "
          f"{survivor_ratio['async']:.2f}x, no eviction, matched loss "
          f"(wall {wall:.1f}s)", flush=True)
    return 0


# ---------------------------------------------------------------------------
# Link-observatory scenario (linkdelay fault -> online estimator + SLO)
# ---------------------------------------------------------------------------

def links_worker_main(args) -> int:
    """One rank of the link-observatory gang: the same barrier-free
    push-sum workload as the async delay leg, with every wire message
    trace-tagged (``BLUEFOG_TPU_TRACE_SAMPLE=1``) so the link
    observatory's online per-edge estimator runs dense.  A ``linkdelay``
    chaos fault holds one rank's outbound DATA links at +``ms`` from
    ``fault_step`` to the END of the run; mid-fault this worker captures
    its ``/healthz`` links block and SLO latch (and proc 0 renders one
    live ``tools top`` frame against every rank's real ``/metrics``
    endpoint), and at the end every rank ships its ``bf_link_*``
    snapshot over the coordinator KV and computes the IDENTICAL merged
    link matrix — the gauge-MAX merge ``bf.link_report()`` performs over
    the aggregate-snapshot collective on a real gang."""
    os.environ.setdefault("BLUEFOG_TPU_TELEMETRY", "1")
    _init_rendezvous()
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.run.supervisor import ChurnSupervisor
    from bluefog_tpu.utils import config, linkobs, telemetry
    config.reload()
    bf.init()
    W.init_transport()
    me = bf.rank()
    nproc = jax.process_count()
    my_proc = jax.process_index()
    W.turn_on_win_ops_with_associated_p()
    target = float(me)
    x = np.zeros(args.dim, np.float32) + target
    name = "links_x"
    W.win_create(np.zeros((1, args.dim), np.float32), name, zero_init=True)
    win = W._store.get(name)
    with win.lock:
        win.main[me][:] = x
    sup = ChurnSupervisor()
    outs = sorted(bf.out_neighbor_ranks(me))
    share = 1.0 / (len(outs) + 1.0)
    dst_w = {o: share for o in outs}
    every = config.get().async_collect_every

    from jax._src import distributed as _dist
    client = _dist.global_state.client
    port = telemetry.start_http_server(0)
    client.key_value_set(f"bf/links_port/{my_proc}", str(port))

    def settle(tag):
        W.win_flush()
        _kv_barrier(tag, my_proc, nproc)
        time.sleep(0.05)
        _kv_barrier(tag + "b", my_proc, nproc)
        W.win_fold_stale_residuals(name)

    def healthz():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:  # 503 when degraded
            return json.loads(e.read().decode())

    # Mid-fault capture point: late enough that the exact-collect
    # backstop has coupled the gang at least once inside the fault
    # window (so the receivers' delay EWMAs have fed on many delayed
    # messages), early enough that the fault is still engaged.
    capture_step = args.fault_step + args.fault_steps - 5
    hz_mid = slo_mid = None
    top_ok = None
    top_lines = 0
    view = None
    steps_run = 0
    for step in range(args.steps):
        change = sup.step(step)
        if change is not None:
            view = change
            if change.evicted:
                break
        W.set_async_step(step)
        telemetry.set_gauge("bf_async_step_lag",
                            float(W.async_step_lag()), rank=str(me))
        p = max(W.win_associated_p(name, me), 1e-3)
        z = x / p
        x = x - args.lr * (z - target) * p
        W.win_accumulate(x[None], name, self_weight=share,
                         dst_weights=dst_w)
        if every and (step + 1) % every == 0:
            settle(f"c{step}")
        x = np.asarray(W.win_update_then_collect(name))[0]
        steps_run += 1
        if step == capture_step:
            hz = healthz()
            hz_mid = {"status": hz.get("status"),
                      "links": hz.get("links")}
            slo_mid = linkobs.slo_state()
            if my_proc == 0:
                # The dashboard leg: one COMPLETE frame against every
                # rank's live endpoint, mid-fault.
                from bluefog_tpu.tools import top as topmod
                eps = []
                for pp in range(nproc):
                    pv = client.blocking_key_value_get(
                        f"bf/links_port/{pp}", 60_000)
                    eps.append(f"127.0.0.1:{pv}")
                polls = {ep: topmod.scrape(ep, timeout=10.0)
                         for ep in eps}
                frame = topmod.render_frame(polls)
                up = sum(1 for mh in polls.values()
                         if mh[0] is not None)
                top_ok = bool(up == nproc and "link matrix" in frame
                              and "DOWN" not in frame)
                top_lines = len(frame.splitlines())
        if args.pace_ms:
            time.sleep(args.pace_ms / 1e3)

    evicted = bool(view is not None and view.evicted)
    info = sup.info()
    if not evicted:
        settle("final")
    # Ship my bf_link_* rows; every rank merges the same four snapshots
    # into the same matrix (report_from_snapshot is pure).
    snap = telemetry.snapshot()
    link_rows = {k: v for k, v in snap.items()
                 if k.startswith("bf_link_")}
    client.key_value_set(f"bf/links_snap/{my_proc}",
                         json.dumps(link_rows))
    snaps = [link_rows if pp == my_proc else json.loads(
        client.blocking_key_value_get(f"bf/links_snap/{pp}", 120_000))
        for pp in range(nproc)]
    report = linkobs.report_from_snapshot(
        linkobs.merge_link_snapshots(snaps))
    cfg = config.get()
    dump_exists = bool(cfg.flight_recorder_path) and os.path.exists(
        f"{cfg.flight_recorder_path}.{me}.bin")
    print(_RESULT_TAG + json.dumps({
        "rank": me,
        "proc": my_proc,
        "mode": "links",
        "steps": steps_run,
        "evicted": evicted,
        "changes_total": info["changes_total"],
        "hot_edge": report.get("hot_edge"),
        "max_divergence": report.get("max_divergence_ratio"),
        "edges": report.get("edges"),
        "slo_mid": slo_mid,
        "hz_mid": hz_mid,
        "slo_breach_counts": {
            k: v for k, v in snap.items()
            if k.startswith("bf_slo_breaches_total")},
        "dump_exists": dump_exists,
        "top_ok": top_ok,
        "top_frame_lines": top_lines,
    }), flush=True)
    active_procs = set() if evicted else set(range(nproc))
    sys.stdout.flush()
    sys.stderr.flush()
    _done_barrier(active_procs, my_proc, args.grace)
    os._exit(0)


def run_links_demo(args) -> int:
    """Driver for ``make links-smoke``: a 4-proc CPU gang with a 60 ms
    ``linkdelay`` fault on one rank's outbound data links, judged on the
    link observatory's whole promise:

      * the affected edges' online delay EWMAs converge on the injected
        delay while every unaffected edge stays flat;
      * measured-vs-modeled divergence on the hot edges crosses the
        alert threshold;
      * exactly the matching SLO rule fires on the receiver ranks —
        breach counter, degraded ``/healthz`` links block, one
        flight-recorder dump — and the co-armed quiet rule never does;
      * every rank computes the IDENTICAL merged link matrix (the
        ``bf.link_report()`` agreement claim, over KV-shipped
        snapshots);
      * ``tools top`` renders one complete frame against the live gang.
    """
    import tempfile

    from bluefog_tpu.utils.linkobs import DIVERGENCE_ALERT
    n = args.np
    delay_rank = (n - 1) if args.delay_rank is None else args.delay_rank
    if delay_rank == 0:
        raise SystemExit("chaos: rank 0 hosts the rendezvous coordinator; "
                         "delay any other rank")
    spec = (f"linkdelay:rank={delay_rank}:step={args.fault_step}"
            f":steps={args.fault_steps}:ms={args.delay_ms}")
    # Breach threshold at a third of the injected delay: a couple of
    # delayed samples push the EWMA past it, and no healthy CPU-loopback
    # edge gets anywhere near it.
    rule = f"link_delay_us>={int(args.delay_ms * 1e3 / 3)}"
    quiet_rule = "step_lag>=100000"
    rec_dir = tempfile.mkdtemp(prefix="bf-links-flightrec-")
    rec_prefix = os.path.join(rec_dir, "flightrec")
    cmd = [sys.executable, "-m", "bluefog_tpu.run", "-np", str(n),
           "--devices-per-proc", "1", "--chaos", spec, "--",
           sys.executable, "-m", "bluefog_tpu.tools", "chaos",
           "--worker", "--mode", "links",
           "--steps", str(args.steps), "--dim", str(args.dim),
           "--lr", str(args.lr), "--pace-ms", str(args.pace_ms),
           "--grace", str(args.grace),
           "--fault-step", str(args.fault_step),
           "--fault-steps", str(args.fault_steps)]
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BLUEFOG_TPU_CHURN": "1",
        "BLUEFOG_TPU_CHURN_HEARTBEAT_MS": "80",
        # Wide suspicion: the fault only delays DATA ops (heartbeats
        # ride undelayed), but a loaded CI box must not turn the slow
        # rank into a churn event mid-measurement.
        "BLUEFOG_TPU_CHURN_SUSPECT_MS": "1500",
        "BLUEFOG_TPU_TELEMETRY": "1",
        # Every message tagged: the estimator feeds on each commit.
        "BLUEFOG_TPU_TRACE_SAMPLE": "1",
        "BLUEFOG_TPU_ASYNC": "1",
        "BLUEFOG_TPU_ASYNC_STALENESS_STEPS": "64",
        # Tight collect cadence: the backstop couples the gang inside
        # the fault window, so the receivers' EWMAs feed on dozens of
        # delayed messages before the mid-fault capture.
        "BLUEFOG_TPU_ASYNC_COLLECT_EVERY":
            str(min(args.collect_every, 20)),
        "BLUEFOG_TPU_FLIGHT_RECORDER": "1",
        "BLUEFOG_TPU_FLIGHT_RECORDER_PATH": rec_prefix,
        "BLUEFOG_TPU_SLO": f"{rule};{quiet_rule}",
    })
    print(f"chaos links: launching {n}-process gang, {spec}, "
          f"SLO \"{rule};{quiet_rule}\" ({args.steps} steps)...",
          flush=True)
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=args.timeout)
    wall = time.perf_counter() - t0
    results = _parse_results(proc.stdout)
    failures = []
    if proc.returncode != 0:
        _fail(failures, f"bfrun exited {proc.returncode}")
    if sorted(results) != list(range(n)):
        _fail(failures, f"expected reports from all {n} ranks, got "
                        f"{sorted(results)}")
    receivers = []
    hot_edges = set()
    if results:
        # The affected edges (and so the expected breach set) come from
        # the merged matrix itself: every edge out of the delayed rank.
        any_rec = next(iter(results.values()))
        affected = [e for e in (any_rec.get("edges") or [])
                    if e["src"] == delay_rank]
        unaffected = [e for e in (any_rec.get("edges") or [])
                      if e["src"] != delay_rank]
        receivers = sorted({e["dst"] for e in affected})
        if not affected:
            _fail(failures, "merged matrix carries no edge out of the "
                            f"delayed rank {delay_rank}")
        if not unaffected:
            _fail(failures, "merged matrix carries no unaffected edge "
                            "to compare against")
        if affected and unaffected:
            lo_aff = min(e["delay_us"] for e in affected)
            hi_un = max(e["delay_us"] for e in unaffected)
            if lo_aff < 0.5 * args.delay_ms * 1e3:
                _fail(failures,
                      f"affected-edge delay EWMA {lo_aff:.0f}us never "
                      f"converged on the injected {args.delay_ms}ms "
                      "(want >= half)")
            if hi_un > 0.5 * lo_aff:
                _fail(failures,
                      f"an unaffected edge reads {hi_un:.0f}us — not "
                      f"flat against the hot edges' {lo_aff:.0f}us")
    for rank, r in sorted(results.items()):
        hot = r.get("hot_edge") or {}
        hot_edges.add((hot.get("src"), hot.get("dst")))
        slo = r.get("slo_mid") or {}
        breached = sorted((slo.get("breached") or {}))
        counts = r.get("slo_breach_counts") or {}
        print(f"  rank {rank}: hot {hot.get('src')}->{hot.get('dst')} "
              f"({hot.get('delay_us', 0):.0f}us), divergence "
              f"x{r.get('max_divergence', 0):.1f}, mid-fault breached "
              f"{breached}, dump={r.get('dump_exists')}", flush=True)
        if r.get("evicted") or r.get("changes_total"):
            _fail(failures, f"rank {rank}: membership churned (a merely "
                            "slow LINK was treated as a dead peer)")
        if hot.get("src") != delay_rank:
            _fail(failures, f"rank {rank}: hot edge {hot} does not "
                            f"leave the delayed rank {delay_rank}")
        if (r.get("max_divergence") or 0.0) <= DIVERGENCE_ALERT:
            _fail(failures,
                  f"rank {rank}: max divergence "
                  f"{r.get('max_divergence')} never crossed the alert "
                  f"threshold {DIVERGENCE_ALERT}")
        want_breach = rank in receivers
        if want_breach:
            if breached != [rule]:
                _fail(failures,
                      f"rank {rank}: mid-fault breach set {breached} != "
                      f"exactly [{rule!r}] (quiet rule must stay quiet)")
            hz = r.get("hz_mid") or {}
            if hz.get("status") != "degraded":
                _fail(failures, f"rank {rank}: /healthz status "
                                f"{hz.get('status')!r} not degraded "
                                "mid-breach")
            links = hz.get("links") or {}
            if rule not in (links.get("slo") or {}).get("breached", []):
                _fail(failures, f"rank {rank}: /healthz links block "
                                f"carries no breach ({links})")
            if not any(rule in k for k in counts):
                _fail(failures, f"rank {rank}: bf_slo_breaches_total "
                                f"never ticked for the rule ({counts})")
            if not r.get("dump_exists"):
                _fail(failures, f"rank {rank}: no flight-recorder dump "
                                "on first breach")
        else:
            if breached:
                _fail(failures, f"rank {rank}: breached {breached} on a "
                                "rank with no delayed in-edge")
            if r.get("dump_exists"):
                _fail(failures, f"rank {rank}: spurious flight-recorder "
                                "dump without a breach")
    if len(hot_edges) > 1:
        _fail(failures, f"ranks disagree on the hot edge: {hot_edges} — "
                        "the merged matrix is not consistent")
    r0 = results.get(0) or {}
    if r0 and r0.get("top_ok") is not True:
        _fail(failures, "tools top did not render a complete frame "
                        f"against the live gang (top_ok={r0.get('top_ok')},"
                        f" {r0.get('top_frame_lines', 0)} lines)")
    import shutil
    shutil.rmtree(rec_dir, ignore_errors=True)
    if failures:
        print("\nchaos links FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        tail = "\n".join(proc.stderr.splitlines()[-40:])
        print(f"\ngang stderr tail:\n{tail}", file=sys.stderr)
        return 1
    print(f"chaos links OK: rank {delay_rank}'s outbound data links held "
          f"at +{args.delay_ms}ms — edges {sorted(hot_edges)} ran hot, "
          f"divergence crossed x{DIVERGENCE_ALERT}, SLO {rule!r} fired on "
          f"ranks {receivers} only (counter + degraded /healthz + dump), "
          f"all ranks agreed on the matrix, top rendered "
          f"{r0.get('top_frame_lines', 0)} lines (wall {wall:.1f}s)",
          flush=True)
    return 0


# ---------------------------------------------------------------------------
# Self-tuning control-plane scenario (linkdelay fault -> re-route epoch)
# ---------------------------------------------------------------------------

def tune_worker_main(args) -> int:
    """One rank of the self-tuning control-plane gang: the async
    push-sum workload started on a FULL MESH — the deliberately wrong
    topology for the coming ``linkdelay`` fault, which sleeps the sender
    once per outbound DATA message, so the delayed rank pays
    ``(n-1) * ms`` per step until something re-routes it.  The tuner is
    that something: at every exact-collect boundary the gang exchanges
    ``bf_link_*`` snapshots over the coordinator KV and feeds the
    IDENTICAL merged matrix, then ticks the tuner inside the quiesced
    barrier window (no data in flight, so a topology swap's window
    free/recreate never races a peer's ``win_accumulate``) — every rank
    derives the same adaptation at the same step.  Per-step wall times
    are segmented into pre-fault / fault-before-epoch / fault-after-
    epoch so the driver can price the recovery."""
    os.environ.setdefault("BLUEFOG_TPU_TELEMETRY", "1")
    _init_rendezvous()
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topology_util
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.run.supervisor import ChurnSupervisor
    from bluefog_tpu.utils import config, telemetry, tuner
    config.reload()
    bf.init()
    W.init_transport()
    me = bf.rank()
    nproc = jax.process_count()
    my_proc = jax.process_index()
    tuned = bool(config.get().tune)
    bf.set_topology(topology_util.FullyConnectedGraph(bf.size()),
                    is_weighted=True)
    W.turn_on_win_ops_with_associated_p()
    target = float(me)
    x = np.zeros(args.dim, np.float32) + target
    name = "tune_x"
    W.win_create(np.zeros((1, args.dim), np.float32), name, zero_init=True)
    win = W._store.get(name)
    with win.lock:
        win.main[me][:] = x
    sup = ChurnSupervisor()
    every = config.get().async_collect_every

    from jax._src import distributed as _dist
    client = _dist.global_state.client
    port = telemetry.start_http_server(0)
    client.key_value_set(f"bf/tune_port/{my_proc}", str(port))

    def send_plan():
        # Re-read EVERY step: a tuner epoch can have re-entered
        # set_topology since the last one.
        return topology_util.GetSendWeights(bf.load_topology(), me)

    def sched_sig():
        self_w, dst_w = send_plan()
        return {"outs": sorted(int(d) for d in dst_w),
                "self_weight": round(float(self_w), 9),
                "dst_weights": {str(int(d)): round(float(w), 9)
                                for d, w in sorted(dst_w.items())}}

    def settle(tag, step):
        W.win_flush()
        _kv_barrier(tag, my_proc, nproc)
        time.sleep(0.05)
        _kv_barrier(tag + "b", my_proc, nproc)
        W.win_fold_stale_residuals(name)
        if step >= args.fault_step:
            # Control-plane exchange at the quiesced boundary.  Both
            # tuner calls are no-ops when BLUEFOG_TPU_TUNE=0.
            snap = telemetry.snapshot()
            rows = {k: v for k, v in snap.items()
                    if k.startswith("bf_link_")}
            client.key_value_set(f"bf/tune_snap/{step}/{my_proc}",
                                 json.dumps(rows))
            snaps = [rows if pp == my_proc else json.loads(
                client.blocking_key_value_get(
                    f"bf/tune_snap/{step}/{pp}", 120_000))
                for pp in range(nproc)]
            tuner.feed_snapshots(snaps)
            tuner.tick(step)
            _kv_barrier(tag + "t", my_proc, nproc)

    def healthz():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:  # 503 when degraded
            return json.loads(e.read().decode())

    sig0 = sched_sig()
    capture_step = args.steps - 5
    hz_mid = None
    top_ok = top_has_epoch = None
    top_lines = 0
    pre_dt = []
    fault_dt = []  # (seconds, tuner epoch at step START)
    view = None
    steps_run = 0
    for step in range(args.steps):
        t0 = time.perf_counter()
        epoch_at = int((tuner.health_summary() or {}).get("epoch", 0))
        change = sup.step(step)
        if change is not None:
            view = change
            if change.evicted:
                break
        W.set_async_step(step)
        telemetry.set_gauge("bf_async_step_lag",
                            float(W.async_step_lag()), rank=str(me))
        p = max(W.win_associated_p(name, me), 1e-3)
        z = x / p
        x = x - args.lr * (z - target) * p
        self_w, dst_w = send_plan()
        W.win_accumulate(x[None], name, self_weight=self_w,
                         dst_weights=dst_w)
        if every and (step + 1) % every == 0:
            settle(f"c{step}", step)
        x = np.asarray(W.win_update_then_collect(name))[0]
        steps_run += 1
        dt = time.perf_counter() - t0
        if step < args.fault_step:
            pre_dt.append(dt)
        else:
            # The adaptation step itself is attributed to the PRE-epoch
            # segment (epoch read at step start): its wall time is mixed.
            fault_dt.append((dt, epoch_at))
        if step == capture_step:
            hz = healthz()
            hz_mid = {"status": hz.get("status"),
                      "tuner": hz.get("tuner")}
            if my_proc == 0:
                # The dashboard leg: one COMPLETE frame against every
                # rank's live endpoint, post-adaptation.
                from bluefog_tpu.tools import top as topmod
                eps = []
                for pp in range(nproc):
                    pv = client.blocking_key_value_get(
                        f"bf/tune_port/{pp}", 60_000)
                    eps.append(f"127.0.0.1:{pv}")
                polls = {ep: topmod.scrape(ep, timeout=10.0)
                         for ep in eps}
                frame = topmod.render_frame(polls)
                up = sum(1 for mh in polls.values()
                         if mh[0] is not None)
                top_ok = bool(up == nproc and "tune" in frame
                              and "DOWN" not in frame)
                top_has_epoch = "1:topology" in frame
                top_lines = len(frame.splitlines())
        if args.pace_ms:
            time.sleep(args.pace_ms / 1e3)

    evicted = bool(view is not None and view.evicted)
    info = sup.info()
    if not evicted:
        W.win_flush()
        _kv_barrier("final", my_proc, nproc)
    th = tuner.health_summary() or {}
    snap = telemetry.snapshot()
    fault_all = [d for d, _ in fault_dt]
    fault_early = [d for d, ep in fault_dt if ep == 0]
    fault_late = [d for d, ep in fault_dt if ep >= 1]
    print(_RESULT_TAG + json.dumps({
        "rank": me,
        "proc": my_proc,
        "mode": "tune",
        "tuned": tuned,
        "steps": steps_run,
        "evicted": evicted,
        "changes_total": info["changes_total"],
        "pre_ms": _robust_window_ms(pre_dt),
        "fault_ms": _robust_window_ms(fault_all),
        "fault_early_ms": _median_ms(fault_early),
        "fault_late_ms": _robust_window_ms(fault_late),
        "n_fault_late": len(fault_late),
        "epoch": int(th.get("epoch", 0)),
        "reverts": int(th.get("reverts", 0)),
        "last_knob": th.get("last_knob"),
        "topology_tag": th.get("topology"),
        "knobs": th.get("knobs"),
        "hz_mid": hz_mid,
        "tune_series": sorted(k for k in snap
                              if k.startswith("bf_tune_")),
        "sig_start": sig0,
        "sig_end": sched_sig(),
        "top_ok": top_ok,
        "top_has_epoch": top_has_epoch,
        "top_frame_lines": top_lines,
    }), flush=True)
    active_procs = set() if evicted else set(range(nproc))
    sys.stdout.flush()
    sys.stderr.flush()
    _done_barrier(active_procs, my_proc, args.grace)
    os._exit(0)


def run_tune_demo(args) -> int:
    """Driver for ``make tune-smoke``: the same 4-proc gang and
    ``linkdelay`` fault run TWICE —

      * ``BLUEFOG_TPU_TUNE=1``: the tuner must commit EXACTLY ONE
        numbered adaptation epoch (every rank agrees on it and on the
        chosen topology), cut the delayed rank's out-degree, recover
        >= ``--tune-ratio`` (default 2x) of the lost gossip throughput
        without any restart, surface the epoch in the ``/healthz``
        "tuner" block and the ``tools top`` tune column, and never
        revert;
      * ``BLUEFOG_TPU_TUNE=0`` pinned: the identical fault must change
        NOTHING — zero ``bf_tune_*`` series registered, no "tuner"
        health block, send schedule bitwise identical start-to-end,
        full-mesh out-degree preserved.

    The recovery lever is structural, not statistical: the fault sleeps
    the sender per outbound DATA message, so full mesh costs the delayed
    rank ``(n-1) * ms`` per step and the re-routed ring costs ``ms`` —
    the throughput ratio is the out-degree ratio."""
    n = args.np
    delay_rank = (n - 1) if args.delay_rank is None else args.delay_rank
    if delay_rank == 0:
        raise SystemExit("chaos: rank 0 hosts the rendezvous coordinator; "
                         "delay any other rank")
    spec = (f"linkdelay:rank={delay_rank}:step={args.fault_step}"
            f":steps={args.fault_steps}:ms={args.delay_ms}")
    cmd = [sys.executable, "-m", "bluefog_tpu.run", "-np", str(n),
           "--devices-per-proc", "1", "--chaos", spec, "--",
           sys.executable, "-m", "bluefog_tpu.tools", "chaos",
           "--worker", "--mode", "tune",
           "--steps", str(args.steps), "--dim", str(args.dim),
           "--lr", str(args.lr), "--pace-ms", str(args.pace_ms),
           "--grace", str(args.grace),
           "--fault-step", str(args.fault_step),
           "--fault-steps", str(args.fault_steps)]
    base_env = dict(os.environ)
    base_env.update({
        "JAX_PLATFORMS": "cpu",
        "BLUEFOG_TPU_CHURN": "1",
        "BLUEFOG_TPU_CHURN_HEARTBEAT_MS": "80",
        "BLUEFOG_TPU_CHURN_SUSPECT_MS": "1500",
        "BLUEFOG_TPU_TELEMETRY": "1",
        "BLUEFOG_TPU_TRACE_SAMPLE": "1",
        "BLUEFOG_TPU_ASYNC": "1",
        "BLUEFOG_TPU_ASYNC_STALENESS_STEPS": "64",
        "BLUEFOG_TPU_ASYNC_COLLECT_EVERY": str(args.collect_every),
        # Loopback delay EWMAs are scheduling noise (tens to hundreds
        # of microseconds, easily 3x apart edge to edge); the injected
        # fault is 100-1000x the floor.  A raised trigger is immune to
        # the noise, still fires on the first post-fault feed, and
        # keeps the "exactly one epoch per change" assertion honest.
        "BLUEFOG_TPU_TUNE_DIVERGENCE": "10",
        "BLUEFOG_TPU_TUNE_DWELL_STEPS": str(max(2, args.collect_every)),
    })
    legs = {}
    walls = {}
    for leg, flag in (("tuned", "1"), ("pinned", "0")):
        env = dict(base_env)
        env["BLUEFOG_TPU_TUNE"] = flag
        print(f"chaos tune [{leg}]: launching {n}-process gang "
              f"(BLUEFOG_TPU_TUNE={flag}), {spec} "
              f"({args.steps} steps)...", flush=True)
        t0 = time.perf_counter()
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=args.timeout)
        walls[leg] = time.perf_counter() - t0
        legs[leg] = (proc, _parse_results(proc.stdout))
    failures = []
    for leg, (proc, results) in legs.items():
        if proc.returncode != 0:
            _fail(failures, f"[{leg}] bfrun exited {proc.returncode}")
        if sorted(results) != list(range(n)):
            _fail(failures, f"[{leg}] expected reports from all {n} "
                            f"ranks, got {sorted(results)}")
        for rank, r in sorted(results.items()):
            print(f"  {leg} rank {rank}: pre {r.get('pre_ms', 0):.1f}ms, "
                  f"fault-early {r.get('fault_early_ms', 0):.1f}ms, "
                  f"fault-late {r.get('fault_late_ms', 0):.1f}ms, "
                  f"epoch {r.get('epoch')} ({r.get('last_knob')}), "
                  f"reverts {r.get('reverts')}, "
                  f"out-degree {len((r.get('sig_end') or {}).get('outs', []))}",
                  flush=True)
            if r.get("evicted") or r.get("changes_total"):
                _fail(failures, f"[{leg}] rank {rank}: membership "
                                "churned (a merely slow link was treated "
                                "as a dead peer)")
    tuned_res = legs["tuned"][1]
    pinned_res = legs["pinned"][1]
    # -- tuned leg: one epoch, cluster agreement, measured recovery -------
    tags = set()
    for rank, r in sorted(tuned_res.items()):
        if r.get("epoch") != 1:
            _fail(failures, f"[tuned] rank {rank}: {r.get('epoch')} "
                            "adaptation epochs != exactly 1 for one "
                            "persistent fault")
        if r.get("reverts"):
            _fail(failures, f"[tuned] rank {rank}: adaptation reverted "
                            "(probation judged the re-route a regression)")
        tags.add(r.get("topology_tag"))
        if "bf_tune_epoch" not in (r.get("tune_series") or []):
            _fail(failures, f"[tuned] rank {rank}: no bf_tune_* series "
                            f"registered ({r.get('tune_series')})")
        tb = (r.get("hz_mid") or {}).get("tuner") or {}
        if int(tb.get("epoch", -1)) != 1:
            _fail(failures, f"[tuned] rank {rank}: /healthz tuner block "
                            f"missing or wrong epoch ({tb})")
    if len(tags) != 1 or None in tags:
        _fail(failures, f"[tuned] ranks disagree on the re-routed "
                        f"topology: {tags} — the measured model is not "
                        "cluster-consistent")
    dr_t = tuned_res.get(delay_rank) or {}
    dr_p = pinned_res.get(delay_rank) or {}
    if dr_t and len((dr_t.get("sig_end") or {}).get("outs", [])) >= n - 1:
        _fail(failures, "[tuned] delayed rank's out-degree was not "
                        "reduced — the adaptation never re-routed it")
    if dr_t and dr_t.get("n_fault_late", 0) < 6:
        _fail(failures, "[tuned] too few post-adaptation steps "
                        f"({dr_t.get('n_fault_late')}) to judge recovery")
    un = float(dr_p.get("fault_ms") or 0.0)
    tu = float(dr_t.get("fault_late_ms") or 0.0)
    ratio = (un / tu) if tu > 0.0 else 0.0
    if ratio < args.tune_ratio:
        _fail(failures, f"delayed rank recovered only {ratio:.2f}x "
                        f"(untuned fault median {un:.1f}ms vs tuned "
                        f"post-adaptation {tu:.1f}ms; want >= "
                        f"{args.tune_ratio}x)")
    r0 = tuned_res.get(0) or {}
    if r0 and (r0.get("top_ok") is not True
               or r0.get("top_has_epoch") is not True):
        _fail(failures, "[tuned] tools top did not render the tune "
                        f"column's epoch (top_ok={r0.get('top_ok')}, "
                        f"has_epoch={r0.get('top_has_epoch')}, "
                        f"{r0.get('top_frame_lines', 0)} lines)")
    # -- pinned leg: BLUEFOG_TPU_TUNE=0 is bitwise inert ------------------
    for rank, r in sorted(pinned_res.items()):
        if r.get("epoch") or r.get("reverts"):
            _fail(failures, f"[pinned] rank {rank}: adapted with the "
                            "tuner off")
        if r.get("tune_series"):
            _fail(failures, f"[pinned] rank {rank}: bf_tune_* series "
                            "registered with BLUEFOG_TPU_TUNE=0: "
                            f"{r.get('tune_series')}")
        if (r.get("hz_mid") or {}).get("tuner") is not None:
            _fail(failures, f"[pinned] rank {rank}: /healthz grew a "
                            "tuner block with the tuner off")
        if r.get("sig_start") != r.get("sig_end"):
            _fail(failures, f"[pinned] rank {rank}: send schedule "
                            "changed under the fault "
                            f"({r.get('sig_start')} -> {r.get('sig_end')})")
        if len((r.get("sig_end") or {}).get("outs", [])) != n - 1:
            _fail(failures, f"[pinned] rank {rank}: full-mesh out-degree "
                            "not preserved")
    if failures:
        print("\nchaos tune FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        for leg, (proc, _) in legs.items():
            tail = "\n".join(proc.stderr.splitlines()[-40:])
            print(f"\n[{leg}] gang stderr tail:\n{tail}", file=sys.stderr)
        return 1
    print(f"chaos tune OK: rank {delay_rank} held at +{args.delay_ms}ms "
          f"on a full mesh — tuner committed exactly 1 epoch "
          f"({sorted(tags)[0]}), recovered {ratio:.1f}x (>= "
          f"{args.tune_ratio}x) of the lost throughput without restart, "
          f"and BLUEFOG_TPU_TUNE=0 stayed bitwise inert "
          f"(walls tuned {walls['tuned']:.1f}s / pinned "
          f"{walls['pinned']:.1f}s)", flush=True)
    return 0


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def _fail(msgs, what):
    msgs.append(what)


def run_demo(args) -> int:
    n = args.np
    if args.spec:
        # The assertions below are kill-shaped (survivor set, recovery
        # bound anchored on the kill step): a --spec override must carry
        # exactly one kill so the harness judges against the right gang.
        # Other fault mixes run under `bfrun --chaos` directly.
        from bluefog_tpu.utils.chaos import killed_ranks, parse_chaos
        kills = killed_ranks(parse_chaos(args.spec))
        if len(kills) != 1:
            raise SystemExit(
                "chaos: --spec must contain exactly one kill fault "
                f"(got {kills}); drive delay/partition-only mixes with "
                "`bfrun --chaos` directly")
        kill_rank = kills[0]
        args.kill_step = next(f.step for f in parse_chaos(args.spec)
                              if f.kind == "kill")
        spec = args.spec
    else:
        kill_rank = (n - 1) if args.kill_rank is None else args.kill_rank
        spec = f"kill:rank={kill_rank}:step={args.kill_step}"
    if kill_rank == 0:
        # The jax rendezvous coordinator lives inside rank 0: its death is
        # a whole-gang loss (every coordination client hard-aborts), not a
        # gossip-churn event.  Production deployments pin the coordinator
        # outside the gang; this harness just refuses the footgun.
        raise SystemExit("chaos: rank 0 hosts the rendezvous coordinator "
                         "and cannot be the kill target — pick any other "
                         "rank")
    survivors = sorted(set(range(n)) - {kill_rank})
    cmd = [sys.executable, "-m", "bluefog_tpu.run", "-np", str(n),
           "--devices-per-proc", "1", "--chaos", spec, "--",
           sys.executable, "-m", "bluefog_tpu.tools", "chaos", "--worker",
           "--steps", str(args.steps), "--dim", str(args.dim),
           "--lr", str(args.lr), "--pace-ms", str(args.pace_ms),
           "--grace", str(args.grace), "--kill-step", str(args.kill_step)]
    import tempfile
    rec_dir = tempfile.mkdtemp(prefix="bf-chaos-flightrec-")
    rec_prefix = os.path.join(rec_dir, "flightrec")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BLUEFOG_TPU_CHURN": "1",
        "BLUEFOG_TPU_CHURN_HEARTBEAT_MS": "80",
        "BLUEFOG_TPU_CHURN_SUSPECT_MS": "500",
        "BLUEFOG_TPU_WIN_RETRIES": "1",
        "BLUEFOG_TPU_WIN_RETRY_BACKOFF_MS": "25",
        "BLUEFOG_TPU_TELEMETRY": "1",
        # Black-box leg: recorder armed + sampled wire trace tags, so the
        # committed membership change makes every survivor dump a
        # postmortem the driver can merge (the CI path for reading the
        # flight recorder after a kill — not just unit tests).
        "BLUEFOG_TPU_FLIGHT_RECORDER": "1",
        "BLUEFOG_TPU_TRACE_SAMPLE": "4",
        "BLUEFOG_TPU_FLIGHT_RECORDER_PATH": rec_prefix,
    })
    print(f"chaos: launching {n}-process gang, {spec} "
          f"({args.steps} steps)...", flush=True)
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=args.timeout)
    wall = time.perf_counter() - t0
    results = _parse_results(proc.stdout)

    failures = []
    if proc.returncode != 0:
        _fail(failures, f"bfrun exited {proc.returncode} (the chaos kill "
                        "must be tolerated, any other failure is real)")
    if sorted(results) != survivors:
        _fail(failures, f"expected reports from survivors {survivors}, "
                        f"got {sorted(results)}")
    target_mean = sum(float(r) for r in survivors) / len(survivors)
    for rank in sorted(results):
        r = results[rank]
        line = (f"  rank {rank}: epoch {r['epoch']}, active "
                f"{r['active_ranks']}, x_mean {r['x_mean']:.4f} "
                f"(target {target_mean:.4f}), recovery@{r['recovery_step']}"
                f", step ms pre/post {r['pre_median_ms']:.2f}/"
                f"{r['post_median_ms']:.2f}, put_errors {r['put_errors']}")
        print(line, flush=True)
        if r["epoch"] < 1:
            _fail(failures, f"rank {rank}: no membership epoch committed")
        if list(r["active_ranks"]) != survivors:
            _fail(failures, f"rank {rank}: active ranks {r['active_ranks']}"
                            f" != survivors {survivors}")
        if r["recovery_step"] is None:
            _fail(failures, f"rank {rank}: never recovered")
        elif r["recovery_step"] - args.kill_step > args.recovery_bound:
            _fail(failures,
                  f"rank {rank}: recovery took "
                  f"{r['recovery_step'] - args.kill_step} steps "
                  f"(bound {args.recovery_bound})")
        if not r["recovery_observed"]:
            _fail(failures, f"rank {rank}: bf_churn_recovery_seconds "
                            "histogram never observed")
        m = r.get("healthz_membership")
        if not m or m.get("epoch", 0) < 1:
            _fail(failures, f"rank {rank}: /healthz carries no committed "
                            f"membership block ({m})")
        if abs(r["x_mean"] - target_mean) > args.loss_tol:
            _fail(failures,
                  f"rank {rank}: consensus value {r['x_mean']:.4f} is "
                  f"{abs(r['x_mean'] - target_mean):.4f} from the "
                  f"survivor optimum {target_mean:.4f} "
                  f"(tol {args.loss_tol})")
        # Step-time regression: medians floored at pace + 5 ms — on a
        # small shared CI box the op time is a few ms and ambient load
        # swings it by more than that, so an anomalously QUIET pre-window
        # must not fabricate a regression a genuinely slow post-recovery
        # path (tens of ms: leftover retries, a peer not dropped) would
        # still trip.
        floor = args.pace_ms + 5.0
        pre = max(r["pre_median_ms"], floor)
        post = max(r["post_median_ms"], floor)
        if post / pre > args.step_ratio:
            _fail(failures, f"rank {rank}: post-recovery step time "
                            f"{post:.2f}ms > {args.step_ratio}x "
                            f"pre-failure {pre:.2f}ms")
    # Flight-recorder postmortem: every survivor dumps its black box at
    # the committed membership change (run/supervisor.py); the dumps must
    # decode into one valid merged trace — the exact artifact an operator
    # reads after a real kill.
    try:
        from bluefog_tpu.tools import tracegossip
        rec_files = tracegossip.dump_files(rec_prefix)
        missing = [r for r in survivors if r not in rec_files]
        if missing:
            _fail(failures, "no flight-recorder dump from survivor(s) "
                            f"{missing} (found {sorted(rec_files)})")
        else:
            dumps = tracegossip.load_dumps(rec_prefix)
            out, stats = tracegossip.merge_gossip(rec_prefix, dumps=dumps)
            with open(out) as f:
                merged = json.load(f)
            lanes = {e.get("pid") for e in merged}
            if not set(survivors) <= lanes:
                _fail(failures, f"merged trace lanes {sorted(lanes)} miss "
                                f"survivors {survivors}")
            print(f"chaos: flight-recorder postmortem OK — "
                  f"{stats['events']} events from ranks {stats['ranks']}, "
                  f"{stats['flows_matched']} cross-rank flow arrow(s)",
                  flush=True)
    except Exception as e:  # noqa: BLE001 — a broken dump IS the failure
        _fail(failures, f"flight-recorder postmortem failed: {e}")
    finally:
        import shutil
        shutil.rmtree(rec_dir, ignore_errors=True)
    if failures:
        print("\nchaos FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        tail = "\n".join(proc.stderr.splitlines()[-40:])
        print(f"\ngang stderr tail:\n{tail}", file=sys.stderr)
        return 1
    print(f"chaos OK: rank {kill_rank} killed at step {args.kill_step}, "
          f"{len(survivors)} survivors re-formed and converged to "
          f"{target_mean:.3f} (wall {wall:.1f}s)", flush=True)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.tools chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--worker", action="store_true",
                   help="internal: run as one gang rank (launched by the "
                        "driver through bfrun)")
    p.add_argument("--mode", default=None,
                   choices=["sync", "async", "links", "tune"],
                   help="internal (with --worker): delay-scenario gossip "
                        "mode — sync steps behind a per-step barrier, "
                        "async is barrier-free push-sum, links is the "
                        "link-observatory leg, tune is the self-tuning "
                        "control-plane leg")
    p.add_argument("--role", default=None, choices=["member", "joiner"],
                   help="internal (with --worker): elastic-leg role — "
                        "member = coordinator-free founding rank, joiner "
                        "= mid-run join via BFTPU_GANG_JOIN")
    p.add_argument("--join-wait", type=float, default=30.0,
                   help="joiner: seconds to wait for the grow epoch to "
                        "commit after the grant")
    p.add_argument("--deadline", type=float, default=None,
                   help="internal: shared unix-time gossip stop point "
                        "for the elastic legs")
    p.add_argument("--run-sec", type=float, default=30.0,
                   help="elastic legs: wall-clock gossip budget (the "
                        "shared deadline every worker stops at)")
    p.add_argument("--join-leg", action="store_true",
                   help="run the elastic JOIN leg: coordinator-free "
                        "4-proc gang, kill a non-zero rank, admit a "
                        "fresh process through the persisted directory, "
                        "assert one grow epoch + full-gang convergence")
    p.add_argument("--kill0-leg", action="store_true",
                   help="run the elastic KILL-RANK-0 leg: same gang, "
                        "SIGKILL rank 0 — the gang must survive (no "
                        "coordinator) and admit a replacement for rank 0")
    p.add_argument("--join-smoke", action="store_true",
                   help="CI smoke profile of the join leg")
    p.add_argument("--kill0-smoke", action="store_true",
                   help="CI smoke profile of the kill-rank-0 leg")
    p.add_argument("--delay", action="store_true",
                   help="run the delay scenario (sync + async legs) "
                        "instead of the kill scenario")
    p.add_argument("--delay-smoke", action="store_true",
                   help="CI smoke profile of the delay scenario")
    p.add_argument("--links", action="store_true",
                   help="run the link-observatory scenario: linkdelay "
                        "fault, online per-edge delay estimation, "
                        "divergence alerting, SLO breach + /healthz + "
                        "flight-recorder dump, cluster-matrix agreement, "
                        "live tools-top frame")
    p.add_argument("--links-smoke", action="store_true",
                   help="CI smoke profile of the link-observatory "
                        "scenario")
    p.add_argument("--tune", action="store_true",
                   help="run the self-tuning control-plane scenario: "
                        "linkdelay fault on a full-mesh gang, tuned "
                        "(BLUEFOG_TPU_TUNE=1) and pinned (=0) legs — "
                        "one adaptation epoch, >= 2x throughput "
                        "recovery, bitwise-inert default")
    p.add_argument("--tune-smoke", action="store_true",
                   help="CI smoke profile of the self-tuning scenario")
    p.add_argument("--tune-ratio", type=float, default=2.0,
                   help="tuned leg's recovery floor: the delayed rank's "
                        "untuned fault step-time median over its tuned "
                        "post-adaptation median must meet this "
                        "(default 2.0)")
    p.add_argument("--delay-rank", type=int, default=None,
                   help="rank the delay fault targets (default: the "
                        "last one)")
    p.add_argument("--delay-ms", type=float, default=60.0,
                   help="per-step sleep the fault injects (default 60)")
    p.add_argument("--fault-step", type=int, default=60,
                   help="first delayed step (past warm-up)")
    p.add_argument("--fault-steps", type=int, default=25,
                   help="how many consecutive steps stay delayed")
    p.add_argument("--collect-every", type=int, default=50,
                   help="async leg's exact-collect backstop cadence")
    p.add_argument("--sync-degrade", type=float, default=3.0,
                   help="sync survivors' fault/pre step-time ratio must "
                        "EXCEED this (proof the lockstep leg couples)")
    p.add_argument("--async-ratio", type=float, default=1.5,
                   help="async survivors' fault/pre step-time ratio must "
                        "stay UNDER this (the ~10%% claim, widened for "
                        "shared-CI noise; the tight bound belongs to the "
                        "quiet multi-host rig)")
    p.add_argument("--np", type=int, default=4,
                   help="gang size (default 4)")
    p.add_argument("--steps", type=int, default=360,
                   help="training steps per rank (default 360)")
    p.add_argument("--dim", type=int, default=128,
                   help="parameter-vector length (default 128)")
    p.add_argument("--lr", type=float, default=0.2)
    p.add_argument("--pace-ms", type=float, default=5.0,
                   help="per-step pacing sleep (stabilizes step-time "
                        "medians on loaded hosts)")
    p.add_argument("--grace", type=float, default=3.0,
                   help="post-loop heartbeat grace before exiting, so "
                        "finish-time skew never reads as churn")
    p.add_argument("--kill-rank", type=int, default=None,
                   help="rank to SIGKILL (default: the last one)")
    p.add_argument("--kill-step", type=int, default=120,
                   help="step at which the kill fires (late enough that "
                        "the pre-failure baseline is measured in steady "
                        "state, past the warm-up)")
    p.add_argument("--spec", default=None,
                   help="full chaos spec override (bfrun --chaos grammar); "
                        "default kill:rank=<kill-rank>:step=<kill-step>")
    p.add_argument("--recovery-bound", type=int, default=250,
                   help="max steps between the kill and the survivors' "
                        "re-plan (default 250)")
    p.add_argument("--loss-tol", type=float, default=0.15,
                   help="|consensus - survivor target mean| bound")
    p.add_argument("--step-ratio", type=float, default=1.5,
                   help="post/pre step-time median bound")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--smoke", action="store_true",
                   help="CI smoke profile (same assertions, smaller run)")
    args = p.parse_args(argv)
    if args.worker:
        if args.role == "member" and os.environ.get("BFTPU_GANG_JOIN"):
            # `bfrun --elastic --grow S` relaunches the SAME command for
            # the late joiner, distinguished only by BFTPU_GANG_JOIN —
            # the same branch a real join-aware training program makes.
            args.role = "joiner"
        if args.role == "member":
            return elastic_worker_main(args)
        if args.role == "joiner":
            return join_worker_main(args)
        if args.mode == "tune":
            return tune_worker_main(args)
        if args.mode == "links":
            return links_worker_main(args)
        if args.mode is not None:
            return delay_worker_main(args)
        return worker_main(args)
    if args.join_leg or args.join_smoke or args.kill0_leg \
            or args.kill0_smoke:
        if args.join_smoke or args.kill0_smoke:
            args.run_sec = min(args.run_sec, 24.0)
            args.dim = min(args.dim, 32)
            args.pace_ms = min(args.pace_ms, 3.0)
            args.kill_step = min(args.kill_step, 80)
        args.steps = max(args.steps, 100_000)  # the deadline governs
        # The combine-what-you-have workload oscillates around the
        # optimum (each step descends before averaging); the elastic
        # legs judge the GANG's mean, so individual ranks get a bit more
        # slack than the kill leg's post-recovery steady state.
        args.loss_tol = max(args.loss_tol, 0.2)
        if args.kill0_leg or args.kill0_smoke:
            return run_elastic_demo(args, kill_rank=0)
        kill_rank = ((args.np - 2 if args.np > 2 else 1)
                     if args.kill_rank is None else args.kill_rank)
        if kill_rank == 0:
            raise SystemExit("chaos --join-leg: use --kill0-leg for the "
                             "rank-0 scenario")
        return run_elastic_demo(args, kill_rank=kill_rank)
    if args.tune or args.tune_smoke:
        if args.tune_smoke:
            args.dim = min(args.dim, 32)
            args.pace_ms = min(args.pace_ms, 3.0)
            args.fault_step = min(args.fault_step, 20)
        # The fault runs to the END of the run, long enough past the
        # adaptation epoch (first post-fault collect boundary + dwell)
        # that the post-adaptation segment carries a stable median; the
        # tight collect cadence is the control-plane exchange cadence.
        args.fault_steps = max(args.fault_steps, 50)
        args.collect_every = min(args.collect_every, 5)
        args.steps = args.fault_step + args.fault_steps
        return run_tune_demo(args)
    if args.links or args.links_smoke:
        if args.links_smoke:
            args.dim = min(args.dim, 32)
            args.pace_ms = min(args.pace_ms, 3.0)
            args.fault_step = min(args.fault_step, 40)
        # The fault runs to the END of the run (EWMAs decay fast once
        # traffic heals — 0.8^40 would erase a converged estimate before
        # the final snapshot), and long enough that collect backstops
        # couple the gang several times inside the fault window.
        args.fault_steps = max(args.fault_steps, 40)
        args.steps = args.fault_step + args.fault_steps
        return run_links_demo(args)
    if args.delay or args.delay_smoke:
        if args.delay_smoke:
            args.steps = min(args.steps, 160)
            args.dim = min(args.dim, 32)
            args.pace_ms = min(args.pace_ms, 3.0)
        return run_delay_demo(args)
    if args.smoke:
        args.steps = min(args.steps, 300)
        args.dim = min(args.dim, 64)
    return run_demo(args)


if __name__ == "__main__":
    sys.exit(main())
