"""metrics-lint: keep code-registered ``bf_*`` metrics and the
``docs/observability.md`` inventory in sync — both directions.

``make metrics-lint`` (part of ``make test``) fails when

  * code registers a ``bf_*`` series (``telemetry.inc`` / ``set_gauge``
    / ``observe`` / ``observe_bucket_counts``) that the observability
    doc never mentions — an UNDOCUMENTED metric; or
  * an inventory-table row in the doc names a metric no code path
    registers — a STALE row left behind by a rename or removal.

Registration sites are found by AST walk over every ``.py`` under
``bluefog_tpu/``: string-literal name arguments of the mutation calls
(``observe_since`` carries the name second; ``"a" if cond else "b"``
conditionals contribute both arms), plus the values of module-level
``*_GAUGES`` / ``*_COUNTERS`` / ``*_METRICS`` name tables (the
convention for names published through a lookup, e.g.
``linkobs._RATE_GAUGES``).  ``clear_gauge``/``clear_counter`` are
hygiene, not registration, and are ignored.

Doc side: the code→doc direction accepts a metric mentioned in
backticks ANYWHERE in the doc; the doc→code direction only audits the
markdown inventory-table rows (lines starting ``| `bf_``), so prose
references to event names, native symbols or out-of-tree metrics
(``bf_bench_phase_seconds`` lives in ``bench.py``) never false-positive.
Histogram suffixes ``_bucket`` / ``_sum`` / ``_count`` are normalized
off both sides; ``name{labels}`` rows and ``a / b`` multi-metric rows
are split.

Pure host lint: no jax, no imports of the package under audit.

  python -m bluefog_tpu.tools.metrics_lint [--root DIR]
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

__all__ = ["registered_metrics", "documented_metrics", "inventory_rows",
           "run_lint", "main"]

_MUTATORS = ("inc", "set_gauge", "observe", "observe_bucket_counts")
# observe_since(t0, "name", ...): the metric name is the SECOND argument.
_MUTATORS_ARG1 = ("observe_since",)
_TABLE_SUFFIX = ("_GAUGES", "_COUNTERS", "_METRICS")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_NAME_RE = re.compile(r"^bf_[a-z0-9_]+$")
_DOC_TOKEN_RE = re.compile(r"`(bf_[a-z0-9_]+)")
_ROW_RE = re.compile(r"^\|\s*`bf_")


def _norm(name: str) -> str:
    for suf in _HIST_SUFFIXES:
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def registered_metrics(root: str) -> Dict[str, List[str]]:
    """``{metric: [file:line, ...]}`` of every ``bf_*`` series the
    package registers."""
    out: Dict[str, List[str]] = {}

    def add(name: str, path: str, lineno: int) -> None:
        if _NAME_RE.match(name):
            out.setdefault(_norm(name), []).append(
                f"{os.path.relpath(path, root)}:{lineno}")

    pkg = os.path.join(root, "bluefog_tpu")
    for dirpath, _, files in os.walk(pkg):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:  # pragma: no cover — broken tree
                raise SystemExit(f"metrics-lint: cannot parse {path}: {e}")
            def name_args(node: ast.Call):
                cn = _call_name(node)
                if cn in _MUTATORS and node.args:
                    yield node.args[0]
                elif cn in _MUTATORS_ARG1 and len(node.args) > 1:
                    yield node.args[1]

            for node in ast.walk(tree):
                if isinstance(node, ast.Call):
                    for arg in name_args(node):
                        if isinstance(arg, ast.IfExp):
                            arms = (arg.body, arg.orelse)
                        else:
                            arms = (arg,)
                        for a in arms:
                            if isinstance(a, ast.Constant) \
                                    and isinstance(a.value, str):
                                add(a.value, path, node.lineno)
                elif isinstance(node, ast.Assign):
                    # *_GAUGES = {"kind": "bf_..."} lookup tables.
                    named = any(
                        isinstance(t, ast.Name)
                        and t.id.endswith(_TABLE_SUFFIX)
                        for t in node.targets)
                    if named and isinstance(node.value, ast.Dict):
                        for v in node.value.values:
                            if isinstance(v, ast.Constant) \
                                    and isinstance(v.value, str):
                                add(v.value, path, v.lineno)
    return out


def documented_metrics(doc_path: str) -> Set[str]:
    """Every backticked ``bf_*`` token anywhere in the doc."""
    with open(doc_path) as f:
        text = f.read()
    return {_norm(m) for m in _DOC_TOKEN_RE.findall(text)}


def inventory_rows(doc_path: str) -> Dict[str, int]:
    """``{metric: first line number}`` from the markdown inventory-table
    rows (``| `bf_...` | type | ... |``)."""
    out: Dict[str, int] = {}
    with open(doc_path) as f:
        for lineno, line in enumerate(f, 1):
            if not _ROW_RE.match(line):
                continue
            first_cell = line.split("|")[1]
            for name in _DOC_TOKEN_RE.findall(first_cell):
                out.setdefault(_norm(name), lineno)
    return out


def run_lint(root: str) -> Tuple[List[str], int, int]:
    """Returns ``(problems, n_registered, n_rows)``."""
    doc = os.path.join(root, "docs", "observability.md")
    if not os.path.exists(doc):
        return ([f"metrics-lint: missing {doc}"], 0, 0)
    reg = registered_metrics(root)
    doc_all = documented_metrics(doc)
    rows = inventory_rows(doc)
    problems: List[str] = []
    for name in sorted(set(reg) - doc_all):
        problems.append(
            f"UNDOCUMENTED metric {name!r} (registered at "
            f"{', '.join(reg[name][:3])}) — add an inventory row to "
            "docs/observability.md")
    for name in sorted(set(rows) - set(reg)):
        problems.append(
            f"STALE inventory row {name!r} "
            f"(docs/observability.md:{rows[name]}) — no code path "
            "registers it; remove or fix the row")
    return problems, len(reg), len(rows)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.tools.metrics_lint",
        description="check code-registered bf_* metrics against the "
                    "docs/observability.md inventory, both directions")
    p.add_argument("--root", default=None,
                   help="repo root (default: two levels above this file)")
    args = p.parse_args(argv)
    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    problems, n_reg, n_rows = run_lint(root)
    for msg in problems:
        print(f"metrics-lint: {msg}", file=sys.stderr)
    if problems:
        print(f"metrics-lint: FAILED ({len(problems)} problem(s); "
              f"{n_reg} registered, {n_rows} inventory rows)",
              file=sys.stderr)
        return 1
    print(f"metrics-lint: OK — {n_reg} registered metric(s) documented, "
          f"{n_rows} inventory row(s) live")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
