"""Offline trace tooling: merge per-rank timelines, summarize phase tails.

``BLUEFOG_TIMELINE=<prefix>`` makes every process write its own
chrome-tracing file ``<prefix><rank>.json`` (``utils/timeline.py``) — but
straggler hunting needs the ranks SIDE BY SIDE on one timeline, which
``chrome://tracing`` cannot do across files.  This package is the merge
step the reference never had:

  python -m bluefog_tpu.tools trace-merge <prefix> [-o merged.json]
      Merge every ``<prefix><rank>.json`` into one trace with one PROCESS
      LANE per rank (pid = rank, named ``rank N``) and aligned clocks:
      each rank's timeline starts with a clock-anchor metadata event
      (``bf_clock_anchor``) pairing its monotonic event clock with wall
      time, so cross-rank skew in the merged view is real wall-clock skew
      (up to NTP error), not per-process clock origin noise.  Tolerates
      and repairs truncated inputs (a killed process never closes its
      JSON array).

  python -m bluefog_tpu.tools trace-summary <merged.json>
      Per-phase p50/p95/p99 duration table from a (merged or single-rank)
      trace's B/E span pairs.
"""

from __future__ import annotations

import argparse
import glob
import json
import re
from typing import Dict, List, Optional, Tuple

__all__ = ["load_trace_events", "rank_files", "trace_merge",
           "phase_durations", "trace_summary", "main"]

_ANCHOR = "bf_clock_anchor"  # timeline.CLOCK_ANCHOR_NAME (no jax import here)


def load_trace_events(path: str) -> Tuple[List[dict], bool]:
    """Parse a chrome-tracing JSON file; returns ``(events, repaired)``.

    Strict parse first; on failure, repair line-by-line — the Python
    timeline writer emits ``[\\n`` then one JSON object per line separated
    by ``,\\n``, so a truncated file (process killed before
    ``stop_timeline``) loses at most its partial tail line."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
        events = data.get("traceEvents", []) if isinstance(data, dict) \
            else data
        return [e for e in events if isinstance(e, dict)], False
    except ValueError:
        pass
    events = []
    body = text.lstrip()
    if body.startswith("["):
        body = body[1:]
    for line in body.splitlines():
        line = line.strip().rstrip(",")
        if not line or line == "]":
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            continue  # the torn tail line of a truncated file
        if isinstance(ev, dict):
            events.append(ev)
    return events, True


def rank_files(prefix: str) -> Dict[int, str]:
    """``{rank: path}`` of the per-rank timelines written under ``prefix``
    (the ``BLUEFOG_TIMELINE`` naming contract: ``<prefix><rank>.json``)."""
    out: Dict[int, str] = {}
    for path in glob.glob(glob.escape(prefix) + "*.json"):
        m = re.fullmatch(re.escape(prefix) + r"(\d+)\.json", path)
        if m:
            out[int(m.group(1))] = path
    return dict(sorted(out.items()))


def _anchor_offset(events: List[dict],
                   path: Optional[str] = None) -> Optional[int]:
    """µs to add to this rank's event timestamps to land on the unix-time
    axis, from its clock-anchor event — or, for the native writer (whose
    wire format cannot carry the anchor in-band), from the
    ``<file>.anchor.json`` sidecar.  None when neither exists
    (pre-anchor files)."""
    for e in events:
        if e.get("name") == _ANCHOR and "args" in e:
            a = e["args"]
            if "unix_us" in a and "monotonic_us" in a:
                return int(a["unix_us"]) - int(a["monotonic_us"])
    if path is not None:
        try:
            with open(path + ".anchor.json") as f:
                a = json.load(f)
            return int(a["unix_us"]) - int(a["monotonic_us"])
        except (OSError, ValueError, KeyError, TypeError):
            pass
    return None


def trace_merge(prefix: str, out_path: Optional[str] = None) -> str:
    """Merge every ``<prefix><rank>.json`` into ``out_path`` (default
    ``<prefix>merged.json``): one process lane per rank, clocks aligned
    via the per-rank anchors.  Returns the output path."""
    files = rank_files(prefix)
    if not files:
        raise FileNotFoundError(
            f"no per-rank timeline files match {prefix}<rank>.json")
    per_rank: Dict[int, List[dict]] = {}
    offsets: Dict[int, Optional[int]] = {}
    repaired_ranks: List[int] = []
    for rank, path in files.items():
        events, repaired = load_trace_events(path)
        per_rank[rank] = events
        offsets[rank] = _anchor_offset(events, path)
        if repaired:
            repaired_ranks.append(rank)
    # Rebase the merged timeline so t=0 is the earliest aligned event
    # (chrome renders absolute-µs traces fine, but small numbers are
    # readable and diffable).
    aligned_starts = [
        min((int(e["ts"]) + off for e in evs if "ts" in e), default=None)
        for r, evs in per_rank.items()
        if (off := offsets[r]) is not None]
    base = min((s for s in aligned_starts if s is not None), default=0)
    merged: List[dict] = []
    for rank, events in per_rank.items():
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0, "ts": 0, "args": {"name": f"rank {rank}"}})
        merged.append({"name": "process_sort_index", "ph": "M", "pid": rank,
                       "tid": 0, "ts": 0, "args": {"sort_index": rank}})
        off = offsets[rank]
        if off is not None:
            shift = off - base
        else:
            # No anchor: this rank cannot be wall-aligned; rebase its own
            # first event to t=0 so its lane is at least readable.
            tmin = min((int(e["ts"]) for e in events if "ts" in e),
                       default=0)
            shift = -tmin
        for e in events:
            if e.get("name") == _ANCHOR:
                continue  # consumed; a lane-local M event would just confuse
            ev = dict(e)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = int(ev["ts"]) + shift
            merged.append(ev)
    if out_path is None:
        out_path = prefix + "merged.json"
    with open(out_path, "w") as f:
        json.dump(merged, f)
    unaligned = sorted(r for r, off in offsets.items() if off is None)
    if unaligned:
        import sys
        print(f"trace-merge: rank(s) {unaligned} carry no clock anchor "
              "(native writer or pre-anchor file); their lanes start at "
              "t=0 instead of wall-aligned", file=sys.stderr)
    if repaired_ranks:
        import sys
        print(f"trace-merge: repaired truncated input for rank(s) "
              f"{repaired_ranks}", file=sys.stderr)
    return out_path


def phase_durations(events: List[dict]) -> Tuple[Dict[str, List[float]],
                                                 int]:
    """``({span name: [duration µs]}, unmatched_begins)`` from B/E pairs
    (per pid/tid/cat/name stack, so nested and concurrent spans pair
    correctly) and complete ``X`` events.

    ``unmatched_begins`` counts B events whose E never arrived — dropped
    under writer-queue overload or lost to file truncation.  Nonzero means
    some durations for those span keys may be unreliable (a later E can
    pair with a stale B and absorb the gap), so the summary must say so
    rather than report an inflated tail silently."""
    stacks: Dict[tuple, List[int]] = {}
    durs: Dict[str, List[float]] = {}
    for e in sorted((e for e in events if "ts" in e),
                    key=lambda e: int(e["ts"])):
        ph = e.get("ph")
        name = e.get("name", "?")
        if ph == "X":
            durs.setdefault(name, []).append(float(e.get("dur", 0)))
            continue
        key = (e.get("pid"), e.get("tid"), e.get("cat"), name)
        if ph == "B":
            stacks.setdefault(key, []).append(int(e["ts"]))
        elif ph == "E":
            st = stacks.get(key)
            if st:
                durs.setdefault(name, []).append(float(int(e["ts"])
                                                       - st.pop()))
    unmatched = sum(len(st) for st in stacks.values())
    return durs, unmatched


def trace_summary(path: str) -> str:
    """Per-phase p50/p95/p99 table (text) from a trace file's spans."""
    import numpy as np
    events, _ = load_trace_events(path)
    durs, unmatched = phase_durations(events)
    if not durs:
        return "trace-summary: no complete spans found"
    rows = []
    for name in sorted(durs, key=lambda n: -sum(durs[n])):
        d = np.asarray(durs[name])
        p50, p95, p99 = np.percentile(d, [50, 95, 99])
        rows.append((name, len(d), d.sum() / 1e3, p50 / 1e3, p95 / 1e3,
                     p99 / 1e3))
    width = max(len(r[0]) for r in rows)
    header = (f"{'phase':<{width}}  {'count':>7}  {'total_ms':>10}  "
              f"{'p50_ms':>9}  {'p95_ms':>9}  {'p99_ms':>9}")
    lines = [header, "-" * len(header)]
    for name, cnt, tot, p50, p95, p99 in rows:
        lines.append(f"{name:<{width}}  {cnt:>7}  {tot:>10.3f}  "
                     f"{p50:>9.3f}  {p95:>9.3f}  {p99:>9.3f}")
    if unmatched:
        lines.append(
            f"WARNING: {unmatched} begin event(s) have no matching end "
            "(dropped under writer overload or truncation) — tail "
            "percentiles for their phases may be inflated")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.tools", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd", required=True)
    pm = sub.add_parser(
        "trace-merge",
        help="merge per-rank BLUEFOG_TIMELINE files into one aligned trace")
    pm.add_argument("prefix", help="the BLUEFOG_TIMELINE prefix the run "
                                   "used (files are <prefix><rank>.json)")
    pm.add_argument("-o", "--output", default=None,
                    help="output path (default <prefix>merged.json)")
    ps = sub.add_parser(
        "trace-summary",
        help="per-phase p50/p95/p99 table from a (merged) trace")
    ps.add_argument("trace", help="trace JSON file (merged or single-rank)")
    args = parser.parse_args(argv)
    if args.cmd == "trace-merge":
        out = trace_merge(args.prefix, args.output)
        events, _ = load_trace_events(out)
        lanes = sorted({e.get("pid") for e in events})
        print(f"trace-merge: wrote {out} ({len(events)} events, "
              f"{len(lanes)} rank lane(s))")
        return 0
    print(trace_summary(args.trace))
    return 0
