"""Offline trace tooling: merge per-rank timelines, summarize phase tails.

``BLUEFOG_TIMELINE=<prefix>`` makes every process write its own
chrome-tracing file ``<prefix><rank>.json`` (``utils/timeline.py``) — but
straggler hunting needs the ranks SIDE BY SIDE on one timeline, which
``chrome://tracing`` cannot do across files.  This package is the merge
step the reference never had:

  python -m bluefog_tpu.tools trace-merge <prefix> [-o merged.json]
      Merge every ``<prefix><rank>.json`` into one trace with one PROCESS
      LANE per rank (pid = rank, named ``rank N``) and aligned clocks:
      each rank's timeline starts with a clock-anchor metadata event
      (``bf_clock_anchor``) pairing its monotonic event clock with wall
      time, so cross-rank skew in the merged view is real wall-clock skew
      (up to NTP error), not per-process clock origin noise.  Tolerates
      and repairs truncated inputs (a killed process never closes its
      JSON array).

  python -m bluefog_tpu.tools trace-summary <merged.json>
      Per-phase p50/p95/p99 duration table from a (merged or single-rank)
      trace's B/E span pairs.

  python -m bluefog_tpu.tools schedule-dump --topology exp2 --n 64 \
          --torus 8x8 [--slices 2] [--sketch auto] [--rounds] \
          [--hier [--hier-outer-every k] [--hier-compression c]]
      Inspect the compiled-schedule pipeline for a topology on a
      simulated torus: one row per pipeline stage (naive shift-distance,
      König repack, congestion repack, sketch synthesis) with provenance,
      round count and the modeled cost triple (max-link-load, hop-bytes,
      serial-link-time), plus the artifact metadata of the schedule the
      selection would dispatch.  ``--hier`` (needs ``--slices >= 2``)
      appends the two-level hierarchical-gossip table: per-level rounds,
      per-step wire rows and the ICI/DCN serial split under the given
      outer cadence and codec.  Pure host math — no accelerator, no
      mesh, no bf.init() required.

  python -m bluefog_tpu.tools trace-gossip <prefix> [-o merged.json] \
          [--json]
      Merge per-rank flight-recorder dumps (``flightrec.<rank>.bin``,
      written by ``BLUEFOG_TPU_FLIGHT_RECORDER`` on fatal transport
      errors / churn events or by ``bf.flight_recorder_dump()``) into
      one chrome trace: a process lane per rank, wall-aligned through
      each dump's clock anchor, with a cross-rank FLOW ARROW per
      sampled wire trace tag (``BLUEFOG_TPU_TRACE_SAMPLE``) — follow
      one put from the sender's enqueue to the receiver's decode.
      Also prints the per-edge one-way-delay p50/p99 table; ``--json``
      emits the stats and the same edge table as one machine-readable
      JSON document instead.  Pure host math over the dump files
      (``tools/tracegossip.py``); runs on whatever survived a chaos
      kill.

  python -m bluefog_tpu.tools top --endpoints host:port,... | \
          --gang-dir <prefix> [--telemetry-base PORT]
      Live fleet dashboard (``tools/top.py``): poll every rank's
      ``/metrics`` + ``/healthz`` each interval and render per-rank
      status / async lag / queue depth / straggler score / SLO state,
      the merged cluster link matrix (the link observatory's
      ``bf_link_*`` gauges, hot edge marked), membership and the
      stalest contribution — one refresh-loop terminal frame, no
      curses.  ``--once`` renders a single frame for scripts and CI.

  python -m bluefog_tpu.tools bench-trend [dir] [--pattern GLOB]
      Perf-trajectory table from the repo's per-round bench records
      (``BENCH_r<N>.json``): one row per round with its rc, the
      headline metric/value/unit, the signed delta against the previous
      round that reported the SAME metric, and the recorded
      vs-baseline factor.  Rounds whose bench had no backend
      (``parsed: null``) render as ``(no parsed result)`` instead of
      vanishing — a gap in the trajectory is itself signal.  Pure
      stdlib over local files.

  python -m bluefog_tpu.tools chaos [--np 4] [--kill-rank K] [--smoke]
      Chaos harness for the churn controller (``tools/chaos.py``): launch
      a CPU multi-process gang under ``bfrun --chaos``, SIGKILL one rank
      mid-gossip, and assert the survivors reach failure consensus,
      re-plan onto a survivor topology without a global restart, converge
      to the survivor optimum, and keep post-recovery step time within
      1.5x the pre-failure median.  ``make chaos-smoke`` runs it in CI.
"""

from __future__ import annotations

import argparse
import glob
import json
import re
from typing import Dict, List, Optional, Tuple

__all__ = ["load_trace_events", "rank_files", "trace_merge",
           "phase_durations", "trace_summary", "schedule_dump",
           "bench_trend", "main"]

_ANCHOR = "bf_clock_anchor"  # timeline.CLOCK_ANCHOR_NAME (no jax import here)


def load_trace_events(path: str) -> Tuple[List[dict], bool]:
    """Parse a chrome-tracing JSON file; returns ``(events, repaired)``.

    Strict parse first; on failure, repair line-by-line — the Python
    timeline writer emits ``[\\n`` then one JSON object per line separated
    by ``,\\n``, so a truncated file (process killed before
    ``stop_timeline``) loses at most its partial tail line."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
        events = data.get("traceEvents", []) if isinstance(data, dict) \
            else data
        return [e for e in events if isinstance(e, dict)], False
    except ValueError:
        pass
    events = []
    body = text.lstrip()
    if body.startswith("["):
        body = body[1:]
    for line in body.splitlines():
        line = line.strip().rstrip(",")
        if not line or line == "]":
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            continue  # the torn tail line of a truncated file
        if isinstance(ev, dict):
            events.append(ev)
    return events, True


def rank_files(prefix: str) -> Dict[int, str]:
    """``{rank: path}`` of the per-rank timelines written under ``prefix``
    (the ``BLUEFOG_TIMELINE`` naming contract: ``<prefix><rank>.json``)."""
    out: Dict[int, str] = {}
    for path in glob.glob(glob.escape(prefix) + "*.json"):
        m = re.fullmatch(re.escape(prefix) + r"(\d+)\.json", path)
        if m:
            out[int(m.group(1))] = path
    return dict(sorted(out.items()))


def _anchor_offset(events: List[dict],
                   path: Optional[str] = None) -> Optional[int]:
    """µs to add to this rank's event timestamps to land on the unix-time
    axis, from its clock-anchor event — or, for the native writer (whose
    wire format cannot carry the anchor in-band), from the
    ``<file>.anchor.json`` sidecar.  None when neither exists
    (pre-anchor files)."""
    for e in events:
        if e.get("name") == _ANCHOR and "args" in e:
            a = e["args"]
            if "unix_us" in a and "monotonic_us" in a:
                return int(a["unix_us"]) - int(a["monotonic_us"])
    if path is not None:
        try:
            with open(path + ".anchor.json") as f:
                a = json.load(f)
            return int(a["unix_us"]) - int(a["monotonic_us"])
        except (OSError, ValueError, KeyError, TypeError):
            pass
    return None


def trace_merge(prefix: str, out_path: Optional[str] = None) -> str:
    """Merge every ``<prefix><rank>.json`` into ``out_path`` (default
    ``<prefix>merged.json``): one process lane per rank, clocks aligned
    via the per-rank anchors.  Returns the output path."""
    files = rank_files(prefix)
    if not files:
        raise FileNotFoundError(
            f"no per-rank timeline files match {prefix}<rank>.json")
    per_rank: Dict[int, List[dict]] = {}
    offsets: Dict[int, Optional[int]] = {}
    repaired_ranks: List[int] = []
    for rank, path in files.items():
        events, repaired = load_trace_events(path)
        per_rank[rank] = events
        offsets[rank] = _anchor_offset(events, path)
        if repaired:
            repaired_ranks.append(rank)
    # Rebase the merged timeline so t=0 is the earliest aligned event
    # (chrome renders absolute-µs traces fine, but small numbers are
    # readable and diffable).
    aligned_starts = [
        min((int(e["ts"]) + off for e in evs if "ts" in e), default=None)
        for r, evs in per_rank.items()
        if (off := offsets[r]) is not None]
    base = min((s for s in aligned_starts if s is not None), default=0)
    merged: List[dict] = []
    for rank, events in per_rank.items():
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0, "ts": 0, "args": {"name": f"rank {rank}"}})
        merged.append({"name": "process_sort_index", "ph": "M", "pid": rank,
                       "tid": 0, "ts": 0, "args": {"sort_index": rank}})
        off = offsets[rank]
        if off is not None:
            shift = off - base
        else:
            # No anchor: this rank cannot be wall-aligned; rebase its own
            # first event to t=0 so its lane is at least readable.
            tmin = min((int(e["ts"]) for e in events if "ts" in e),
                       default=0)
            shift = -tmin
        for e in events:
            if e.get("name") == _ANCHOR:
                continue  # consumed; a lane-local M event would just confuse
            ev = dict(e)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = int(ev["ts"]) + shift
            merged.append(ev)
    if out_path is None:
        out_path = prefix + "merged.json"
    with open(out_path, "w") as f:
        json.dump(merged, f)
    unaligned = sorted(r for r, off in offsets.items() if off is None)
    if unaligned:
        import sys
        print(f"trace-merge: rank(s) {unaligned} carry no clock anchor "
              "(native writer or pre-anchor file); their lanes start at "
              "t=0 instead of wall-aligned", file=sys.stderr)
    if repaired_ranks:
        import sys
        print(f"trace-merge: repaired truncated input for rank(s) "
              f"{repaired_ranks}", file=sys.stderr)
    return out_path


def phase_durations(events: List[dict]) -> Tuple[Dict[str, List[float]],
                                                 int]:
    """``({span name: [duration µs]}, unmatched_begins)`` from B/E pairs
    (per pid/tid/cat/name stack, so nested and concurrent spans pair
    correctly) and complete ``X`` events.

    ``unmatched_begins`` counts B events whose E never arrived — dropped
    under writer-queue overload or lost to file truncation.  Nonzero means
    some durations for those span keys may be unreliable (a later E can
    pair with a stale B and absorb the gap), so the summary must say so
    rather than report an inflated tail silently."""
    stacks: Dict[tuple, List[int]] = {}
    durs: Dict[str, List[float]] = {}
    for e in sorted((e for e in events if "ts" in e),
                    key=lambda e: int(e["ts"])):
        ph = e.get("ph")
        name = e.get("name", "?")
        if ph == "X":
            durs.setdefault(name, []).append(float(e.get("dur", 0)))
            continue
        key = (e.get("pid"), e.get("tid"), e.get("cat"), name)
        if ph == "B":
            stacks.setdefault(key, []).append(int(e["ts"]))
        elif ph == "E":
            st = stacks.get(key)
            if st:
                durs.setdefault(name, []).append(float(int(e["ts"])
                                                       - st.pop()))
    unmatched = sum(len(st) for st in stacks.values())
    return durs, unmatched


def trace_summary(path: str) -> str:
    """Per-phase p50/p95/p99 table (text) from a trace file's spans."""
    import numpy as np
    events, _ = load_trace_events(path)
    durs, unmatched = phase_durations(events)
    if not durs:
        return "trace-summary: no complete spans found"
    rows = []
    for name in sorted(durs, key=lambda n: -sum(durs[n])):
        d = np.asarray(durs[name])
        p50, p95, p99 = np.percentile(d, [50, 95, 99])
        rows.append((name, len(d), d.sum() / 1e3, p50 / 1e3, p95 / 1e3,
                     p99 / 1e3))
    width = max(len(r[0]) for r in rows)
    header = (f"{'phase':<{width}}  {'count':>7}  {'total_ms':>10}  "
              f"{'p50_ms':>9}  {'p95_ms':>9}  {'p99_ms':>9}")
    lines = [header, "-" * len(header)]
    for name, cnt, tot, p50, p95, p99 in rows:
        lines.append(f"{name:<{width}}  {cnt:>7}  {tot:>10.3f}  "
                     f"{p50:>9.3f}  {p95:>9.3f}  {p99:>9.3f}")
    if unmatched:
        lines.append(
            f"WARNING: {unmatched} begin event(s) have no matching end "
            "(dropped under writer overload or truncation) — tail "
            "percentiles for their phases may be inflated")
    return "\n".join(lines)


def schedule_dump(topology: str, n: int, torus: str, *, slices: int = 1,
                  degree: int = 4, seed: int = 0, sketch: str = "auto",
                  budget: float = 2.0, optimize_placement: bool = False,
                  show_rounds: bool = False, hier: bool = False,
                  hier_outer_every: int = 1,
                  hier_compression: str = "none",
                  lowering: str = "ppermute", fusion_buckets: int = 4,
                  payload_mb: float = 64.0, sharded: bool = False,
                  replicated_frac: float = 0.5,
                  num_shards: int = 4) -> str:
    """Text report of the schedule pipeline for one topology x torus.

    The artifact refactor makes this nearly free: every stage returns a
    ``CompiledSchedule`` carrying its own provenance, and the cost model
    prices any of them — the dump just lines them up."""
    import numpy as np

    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import placement as PL
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.ops import schedule_opt as SO
    from bluefog_tpu.ops import synthesis as SY

    makers = {
        "ring": lambda: topo.RingGraph(n),
        "exp2": lambda: topo.ExponentialTwoGraph(n),
        "star": lambda: topo.StarGraph(n),
        "random-regular": lambda: topo.RandomRegularGraph(n, degree,
                                                          seed=seed),
    }
    if topology not in makers:
        raise SystemExit(
            f"schedule-dump: unknown topology {topology!r}; expected one "
            f"of {', '.join(sorted(makers))}")
    if sketch != "auto" and sketch not in SY.SKETCHES:
        raise SystemExit(
            f"schedule-dump: unknown sketch {sketch!r}; expected one of "
            f"auto, {', '.join(SY.SKETCHES)}")
    dims = PL.parse_torus_spec(torus)
    model = PL.synthetic_torus(dims, n_slices=slices)
    if len(model.device_node) != n:
        raise SystemExit(
            f"schedule-dump: torus {torus} x {slices} slice(s) has "
            f"{len(model.device_node)} nodes but --n is {n}")
    w = topo.weight_matrix(makers[topology]())
    naive = S._build_schedule(w, optimize=False)
    konig = SO.optimize_schedule(naive)
    perm = None
    placement_note = "identity"
    if optimize_placement:
        res = PL.optimize_placement(model, konig, n, seed=0)
        perm = res.perm
        placement_note = ("identity (optimal)" if res.is_identity
                          else "optimized")
    packed = SO.congestion_aware_repack(konig, model, perm,
                                        budget_factor=budget, record=False)
    chosen, ratio = SY.select_schedule(konig, packed, model, perm,
                                       sketch=sketch, budget_factor=budget)
    stages = [("naive", naive), ("konig", konig), ("congestion", packed)]
    if chosen is not packed:
        stages.append((S.schedule_provenance(chosen), chosen))
    if lowering == "fused":
        # The fused-step consumer re-tags the dispatched artifact, same
        # as ops/fused_step.compile_fused_schedule does before reading
        # window_plan() back off it.
        chosen = S.as_compiled(chosen, lowering="fused")
    lines = [
        f"schedule-dump: {topology} over {n} ranks on {model.name} "
        f"({slices} slice(s)), placement={placement_note}, "
        f"sketch={sketch}, round budget={budget}x Konig",
        "",
        f"{'stage':<28} {'rounds':>6} {'max_link_load':>13} "
        f"{'hop_bytes':>10} {'serial_link_time':>16} {'lowering':>10}",
    ]
    lines.append("-" * len(lines[-1]))
    for name, sched in stages:
        c = PL.schedule_cost(model, sched, perm)
        lines.append(f"{name:<28} {len(sched.rounds):>6} "
                     f"{c.max_link_load:>13.1f} {c.hop_bytes:>10.1f} "
                     f"{c.serial_link_time:>16.1f} "
                     f"{getattr(sched, 'lowering', 'ppermute'):>10}")
    lines += [
        "",
        f"dispatched: provenance={S.schedule_provenance(chosen)} "
        f"sketch={getattr(chosen, 'sketch', None)} "
        f"lowering={getattr(chosen, 'lowering', 'ppermute')} "
        f"synth improvement={ratio:.3f}x"
        + ("" if ratio > 1.0 else " (packed retained — tie or no win)"),
    ]
    if lowering == "fused":
        from bluefog_tpu.ops import fused_step as FS
        total = int(payload_mb * (1 << 20))
        k = max(1, int(fusion_buckets))
        per = [total // k + (1 if i < total % k else 0) for i in range(k)]
        lines += [
            "",
            f"fused lowering preview ({k} bucket(s) over "
            f"{payload_mb:g} MB — whole-step compilation pipelines each "
            "bucket's put against the remaining update compute):",
            f"{'bucket':>6} {'bytes':>12} {'ready_at':>9} {'overlap':>8}",
        ]
        for r in FS.modeled_overlap(per):
            lines.append(f"{r['bucket']:>6} {r['bytes']:>12} "
                         f"{r['ready_at']:>9.2f} {r['overlap']:>8.2f}")
    if show_rounds:
        lines.append("")
        node = np.asarray(model.device_node, np.int64)
        p = np.arange(n) if perm is None else np.asarray(perm, np.int64)
        for i, rnd in enumerate(chosen.rounds):
            loads = np.zeros(model.n_links)
            for s, d in rnd.pairs:
                r = model.route(int(node[p[s]]), int(node[p[d]]))
                np.add.at(loads, r, 1.0)
            b = float((loads * model.link_weights).max()) if rnd.pairs \
                else 0.0
            lines.append(f"round {i:>3}: {len(rnd.pairs):>4} edges, "
                         f"bottleneck {b:.1f}  "
                         f"{list(rnd.pairs)[:8]}"
                         + (" ..." if len(rnd.pairs) > 8 else ""))
    if hier:
        lines.append("")
        lines.extend(_hier_dump_lines(
            model, n, slices, hier_outer_every, hier_compression))
    if sharded:
        lines.append("")
        lines.extend(_sharded_dump_lines(
            model, chosen, n, num_shards, replicated_frac, perm))
    return "\n".join(lines)


def _hier_dump_lines(model, n: int, slices: int, outer_every: int,
                     compression: str) -> List[str]:
    """Two-level schedule/cost table for ``schedule-dump --hier``: one row
    per level (plus one per outer phase) with round count, per-step wire
    rows and the modeled (ICI serial, DCN serial) split — the BENCH-json
    ``detail.hierarchy`` numbers in table form."""
    import numpy as np

    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import placement as PL
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.utils import config as _config

    if slices < 2:
        raise SystemExit(
            "schedule-dump --hier needs --slices >= 2 (a single slice "
            "has no DCN level to split against)")
    try:
        factor = _config.compression_byte_factor(compression)
    except ValueError as e:
        raise SystemExit(f"schedule-dump --hier: {e}")
    ht = topo.hierarchical_two_level(n, slices,
                                     outer_every=max(outer_every, 1))
    first_dcn = model.first_dcn_link

    def split_serial(sched):
        node = np.asarray(model.device_node, np.int64)
        ici = dcn = 0.0
        for rnd in sched.rounds:
            loads = np.zeros(model.n_links)
            for s, d in rnd.pairs:
                np.add.at(loads, model.route(int(node[s]), int(node[d])),
                          1.0)
            ici += float(loads[:first_dcn].max(initial=0.0))
            dcn += float((loads[first_dcn:] * model.dcn_link_cost)
                         .max(initial=0.0))
        return ici, dcn

    inner_sched = S._build_schedule(ht.inner_full_matrix(), optimize=True)
    rows = [("inner (ici, every step)", inner_sched, 1.0, 1.0)]
    for p in range(len(ht.outer_phases)):
        sched = S._build_schedule(ht.outer_full_matrix(p), optimize=True)
        rows.append((f"outer phase {p} (dcn, every {ht.outer_every})",
                     sched, factor, 1.0 / ht.outer_every))
    out = [
        f"hierarchy: {slices} slices of {ht.slice_size}, inner=exp2, "
        f"outer=exp2 one-peer, outer_every={ht.outer_every}, "
        f"outer compression={compression} (byte factor {factor}), "
        f"outer self weight={ht.outer_self_weight}",
        "",
        f"{'level':<28} {'rounds':>6} {'rows/step':>10} "
        f"{'ici_serial':>10} {'dcn_serial':>10}",
    ]
    out.append("-" * len(out[-1]))
    for name, sched, byte_f, cadence_f in rows:
        edges = sum(len(r.pairs) for r in sched.rounds)
        ici, dcn = split_serial(sched)
        out.append(
            f"{name:<28} {len(sched.rounds):>6} "
            f"{edges * byte_f * cadence_f:>10.1f} "
            f"{ici * cadence_f:>10.1f} "
            f"{dcn * byte_f * cadence_f:>10.1f}")
    return out


def _sharded_dump_lines(model, full_sched, n: int, num_shards: int,
                        replicated_frac: float, perm) -> List[str]:
    """Per-replica-group table for ``schedule-dump --sharded``: the
    replicated fraction of the tree rides the full topology while each
    sharded slice gossips inside its replica group only — one row per
    group with its round count, per-step wire rows and modeled serial
    cost, plus the merged in-group artifact all groups dispatch as."""
    from types import SimpleNamespace

    from bluefog_tpu.ops import placement as PL
    from bluefog_tpu.ops import sharded as SH

    if n % num_shards:
        raise SystemExit(
            f"schedule-dump --sharded: --num-shards {num_shards} must "
            f"divide --n {n}")
    if not 0.0 <= replicated_frac <= 1.0:
        raise SystemExit("schedule-dump --sharded: --replicated-frac "
                         "must be in [0, 1]")
    groups = SH.default_groups(n, num_shards)
    merged, per_group = SH.compile_group_schedules(n, groups)
    coords = tuple(next(c for c, g in enumerate(groups) if r in g)
                   for r in range(n))
    rep_rows = replicated_frac          # rows per unit payload row
    sh_rows = (1.0 - replicated_frac) / num_shards
    full_edges = sum(len(r.pairs) for r in full_sched.rounds)
    c_full = PL.schedule_cost(model, full_sched, perm)
    out = [
        f"sharded gossip: {num_shards} replica group(s) of "
        f"{n // num_shards}, replicated fraction "
        f"{replicated_frac:.2f} (sharded slices never leave their "
        "group — DCN bytes scale with the replicated fraction only)",
        "",
        f"{'component':<26} {'ranks':<12} {'rounds':>6} "
        f"{'rows/step':>10} {'max_link_load':>13} "
        f"{'serial_link_time':>16}",
    ]
    out.append("-" * len(out[-1]))
    out.append(
        f"{'replicated (full topo)':<26} {'0-' + str(n - 1):<12} "
        f"{len(full_sched.rounds):>6} {full_edges * rep_rows:>10.2f} "
        f"{c_full.max_link_load * rep_rows:>13.2f} "
        f"{c_full.serial_link_time * rep_rows:>16.2f}")
    for gi, (ranks, sub) in enumerate(per_group):
        # Price this group's slice of the merged artifact in isolation:
        # its pairs on the real torus routes, other groups silent.
        gset = set(ranks)
        rounds = [SimpleNamespace(
            pairs=[(s, d) for (s, d) in rnd.pairs if s in gset])
            for rnd in merged.rounds]
        gsched = SimpleNamespace(rounds=rounds)
        cg = PL.schedule_cost(model, gsched, perm)
        edges = sum(len(r.pairs) for r in rounds)
        span = f"{min(ranks)}-{max(ranks)}" if len(ranks) > 1 \
            else str(ranks[0])
        out.append(
            f"{'group %d (in-group)' % gi:<26} {span:<12} "
            f"{len(sub.rounds):>6} {edges * sh_rows:>10.2f} "
            f"{cg.max_link_load * sh_rows:>13.2f} "
            f"{cg.serial_link_time * sh_rows:>16.2f}")
    ici, dcn = SH.edge_level_counts(coords, merged)
    cm = PL.schedule_cost(model, merged, perm)
    out.append(
        f"{'merged in-group artifact':<26} {'0-' + str(n - 1):<12} "
        f"{len(merged.rounds):>6} "
        f"{(ici + dcn) * sh_rows:>10.2f} "
        f"{cm.max_link_load * sh_rows:>13.2f} "
        f"{cm.serial_link_time * sh_rows:>16.2f}")
    _, full_dcn = SH.edge_level_counts(coords, full_sched)
    out += [
        "",
        f"per-step DCN rows: replicated {full_dcn * rep_rows:.2f} "
        f"(= {replicated_frac:.0%} of the all-replicated "
        f"{full_dcn:.0f}), sharded {dcn * sh_rows:.2f} (in-group "
        "schedules cross no group boundary)",
    ]
    return out


def bench_trend(directory: str = ".",
                pattern: str = "BENCH_r*.json") -> str:
    """Perf-trajectory table from the repo's per-round bench records.

    Every growth round leaves a ``BENCH_r<N>.json`` (``{"n", "cmd",
    "rc", "tail", "parsed"}``; ``parsed`` is the bench's one-line JSON
    result, or null when the round had no backend).  This tabulates them
    into the trajectory the individual files cannot show: one row per
    round with the headline metric, and the delta against the previous
    round that reported the SAME metric — so a perf regression shows up
    as a signed percentage, not a diff between two JSON blobs.  Pure
    stdlib over local files; no jax, no network."""
    import os
    rows = []
    for path in sorted(glob.glob(os.path.join(directory, pattern))):
        name = os.path.basename(path)
        m = re.search(r"r(\d+)", name)
        rnd = int(m.group(1)) if m else -1
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = None
        rows.append((rnd, name, doc))
    if not rows:
        # A rounds directory can carry only multichip-probe records
        # (CPU-only rigs never write BENCH_r*.json) — still tabulate.
        multichip = _multichip_trend(directory)
        if multichip:
            return "\n".join(multichip)
        return (f"bench-trend: no files match "
                f"{os.path.join(directory, pattern)}")
    lines = [f"{'round':>5}  {'rc':>3}  {'metric':<44} {'value':>12}  "
             f"{'unit':<8} {'vs_prev':>8}  {'vs_base':>8}"]
    lines.append("-" * len(lines[0]))
    last_value: Dict[str, float] = {}
    for rnd, name, doc in sorted(rows):
        if doc is None:
            lines.append(f"{rnd:>5}  {'?':>3}  "
                         f"{'<unreadable: ' + name + '>':<44}")
            continue
        rc = doc.get("rc")
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            lines.append(f"{rnd:>5}  {rc if rc is not None else '?':>3}  "
                         f"{'(no parsed result)':<44}")
            continue
        metric = str(parsed.get("metric", "?"))
        value = parsed.get("value")
        unit = str(parsed.get("unit", ""))
        base = parsed.get("vs_baseline")
        prev_txt = "-"
        if isinstance(value, (int, float)):
            prev = last_value.get(metric)
            if prev:
                prev_txt = f"{(value / prev - 1.0) * 100:+.1f}%"
            last_value[metric] = float(value)
        val_txt = (f"{value:g}" if isinstance(value, (int, float))
                   else "-")
        base_txt = (f"{base:g}x" if isinstance(base, (int, float))
                    else "-")
        lines.append(f"{rnd:>5}  {rc if rc is not None else '?':>3}  "
                     f"{metric:<44} {val_txt:>12}  {unit:<8} "
                     f"{prev_txt:>8}  {base_txt:>8}")
    multichip = _multichip_trend(directory)
    if multichip:
        lines.append("")
        lines.extend(multichip)
    return "\n".join(lines)


def _multichip_trend(directory: str,
                     pattern: str = "MULTICHIP_r*.json") -> List[str]:
    """The multichip-probe trajectory next to the bench one.  These
    records carry a different shape (``{"n_devices", "rc", "ok",
    "skipped", "tail"}`` — no ``parsed`` metric: the probe reports
    whether a >1-chip gang came up, not a number), so they get their own
    pass/skip table rather than rows forced into the bench columns."""
    import os
    rows = []
    for path in sorted(glob.glob(os.path.join(directory, pattern))):
        name = os.path.basename(path)
        m = re.search(r"r(\d+)", name)
        rnd = int(m.group(1)) if m else -1
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = None
        rows.append((rnd, name, doc))
    if not rows:
        return []
    lines = [f"{'round':>5}  {'rc':>3}  {'devices':>8}  {'result':<10}"]
    lines.append("-" * len(lines[0]))
    for rnd, name, doc in sorted(rows):
        if doc is None:
            lines.append(f"{rnd:>5}  {'?':>3}  {'?':>8}  "
                         f"<unreadable: {name}>")
            continue
        rc = doc.get("rc")
        result = ("skip" if doc.get("skipped")
                  else "ok" if doc.get("ok") else "FAIL")
        nd = doc.get("n_devices")
        lines.append(f"{rnd:>5}  {rc if rc is not None else '?':>3}  "
                     f"{nd if nd is not None else '?':>8}  {result:<10}")
    return lines


def main(argv=None) -> int:
    import sys
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "chaos":
        # The chaos harness owns a rich flag surface (and a --worker mode
        # bfrun re-enters); delegate before the subparser dispatch.
        from bluefog_tpu.tools.chaos import main as chaos_main
        return chaos_main(argv[1:])
    if argv and argv[0] == "top":
        # Same delegation: the dashboard owns its flag surface.
        from bluefog_tpu.tools.top import main_top
        return main_top(argv[1:])
    parser = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.tools", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd", required=True)
    pm = sub.add_parser(
        "trace-merge",
        help="merge per-rank BLUEFOG_TIMELINE files into one aligned trace")
    pm.add_argument("prefix", help="the BLUEFOG_TIMELINE prefix the run "
                                   "used (files are <prefix><rank>.json)")
    pm.add_argument("-o", "--output", default=None,
                    help="output path (default <prefix>merged.json)")
    ps = sub.add_parser(
        "trace-summary",
        help="per-phase p50/p95/p99 table from a (merged) trace")
    ps.add_argument("trace", help="trace JSON file (merged or single-rank)")
    pg = sub.add_parser(
        "trace-gossip",
        help="merge per-rank flight-recorder dumps into one chrome trace "
             "with cross-rank gossip flow arrows + a per-edge one-way-"
             "delay table")
    pg.add_argument("prefix",
                    help="the BLUEFOG_TPU_FLIGHT_RECORDER_PATH prefix the "
                         "run used (dumps are <prefix>.<rank>.bin)")
    pg.add_argument("-o", "--output", default=None,
                    help="output path (default <prefix>.merged.json)")
    pg.add_argument("--json", action="store_true",
                    help="emit stats + the per-edge delay table as one "
                         "machine-readable JSON document on stdout")
    pb = sub.add_parser(
        "bench-trend",
        help="perf-trajectory table from the per-round BENCH_r*.json "
             "records: one row per round with the headline metric and "
             "the delta vs the previous round reporting it")
    pb.add_argument("directory", nargs="?", default=".",
                    help="directory holding the BENCH_r*.json files "
                         "(default: current directory)")
    pb.add_argument("--pattern", default="BENCH_r*.json",
                    help="glob for the bench records "
                         "(default BENCH_r*.json)")
    # Listed for --help only; the real dispatch happens above (the chaos
    # harness owns its own flag surface, including the bfrun-launched
    # --worker mode).
    sub.add_parser(
        "chaos", add_help=False,
        help="churn-controller chaos harness: kill a gang rank mid-gossip "
             "under bfrun --chaos and assert survivor-only recovery")
    sub.add_parser(
        "top", add_help=False,
        help="live fleet dashboard: poll every rank's /metrics + /healthz "
             "and render the link matrix, stragglers, SLO state and "
             "membership in one refreshing terminal frame")
    pd = sub.add_parser(
        "schedule-dump",
        help="compiled-schedule pipeline report (provenance, rounds, "
             "modeled cost per stage) for a topology on a simulated torus")
    pd.add_argument("--topology", default="exp2",
                    help="ring / exp2 / star / random-regular (default exp2)")
    pd.add_argument("--n", type=int, default=64,
                    help="rank count (must equal torus nodes x slices)")
    pd.add_argument("--torus", default="8x8",
                    help="per-slice torus spec, e.g. 8x8 (default)")
    pd.add_argument("--slices", type=int, default=1,
                    help="DCN-connected slice count (default 1)")
    pd.add_argument("--degree", type=int, default=4,
                    help="random-regular degree (default 4)")
    pd.add_argument("--seed", type=int, default=0,
                    help="random-regular seed (default 0)")
    pd.add_argument("--sketch", default="auto",
                    help="synthesis sketch (default auto)")
    pd.add_argument("--budget", type=float, default=2.0,
                    help="round budget x Konig (default 2.0)")
    pd.add_argument("--optimize-placement", action="store_true",
                    help="price under the optimized placement permutation "
                         "instead of identity")
    pd.add_argument("--rounds", action="store_true",
                    help="also list the dispatched artifact's rounds with "
                         "per-round bottlenecks")
    pd.add_argument("--hier", action="store_true",
                    help="append the two-level hierarchical-gossip table: "
                         "per-level rounds, per-step wire rows and the "
                         "ICI/DCN serial-time split (needs --slices >= 2)")
    pd.add_argument("--hier-outer-every", type=int, default=1,
                    help="--hier: outer (DCN) cadence (default 1)")
    pd.add_argument("--hier-compression", default="none",
                    help="--hier: outer codec none / bf16 / sparse:<frac> "
                         "(default none)")
    pd.add_argument("--lowering", default="ppermute",
                    choices=["ppermute", "fused"],
                    help="dispatch target to preview: 'fused' re-tags the "
                         "chosen schedule for the whole-step compiler "
                         "(BLUEFOG_TPU_FUSED_STEP) and appends the "
                         "modeled per-bucket put/compute overlap table")
    pd.add_argument("--fusion-buckets", type=int, default=4,
                    help="--lowering fused: bucket count for the overlap "
                         "preview (default 4)")
    pd.add_argument("--payload-mb", type=float, default=64.0,
                    help="--lowering fused: modeled per-step payload in "
                         "MB split across the buckets (default 64)")
    pd.add_argument("--sharded", action="store_true",
                    help="append the sharding-aware gossip table "
                         "(BLUEFOG_TPU_SHARDED_GOSSIP): per-replica-"
                         "group rounds, per-step wire rows and modeled "
                         "serial cost, with the DCN rows scaling by "
                         "--replicated-frac")
    pd.add_argument("--replicated-frac", type=float, default=0.5,
                    help="--sharded: replicated byte fraction of the "
                         "tree (default 0.5)")
    pd.add_argument("--num-shards", type=int, default=4,
                    help="--sharded: replica group count; must divide "
                         "--n (default 4)")
    args = parser.parse_args(argv)
    if args.cmd == "schedule-dump":
        print(schedule_dump(
            args.topology, args.n, args.torus, slices=args.slices,
            degree=args.degree, seed=args.seed, sketch=args.sketch,
            budget=args.budget, optimize_placement=args.optimize_placement,
            show_rounds=args.rounds, hier=args.hier,
            hier_outer_every=args.hier_outer_every,
            hier_compression=args.hier_compression,
            lowering=args.lowering, fusion_buckets=args.fusion_buckets,
            payload_mb=args.payload_mb, sharded=args.sharded,
            replicated_frac=args.replicated_frac,
            num_shards=args.num_shards))
        return 0
    if args.cmd == "bench-trend":
        print(bench_trend(args.directory, args.pattern))
        return 0
    if args.cmd == "trace-gossip":
        from bluefog_tpu.tools.tracegossip import main_trace_gossip
        return main_trace_gossip(args.prefix, args.output,
                                 as_json=args.json)
    if args.cmd == "trace-merge":
        out = trace_merge(args.prefix, args.output)
        events, _ = load_trace_events(out)
        lanes = sorted({e.get("pid") for e in events})
        print(f"trace-merge: wrote {out} ({len(events)} events, "
              f"{len(lanes)} rank lane(s))")
        return 0
    print(trace_summary(args.trace))
    return 0
