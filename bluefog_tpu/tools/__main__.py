"""``python -m bluefog_tpu.tools`` — trace-merge / trace-summary CLI."""

import sys

from bluefog_tpu.tools import main

if __name__ == "__main__":
    sys.exit(main())
