"""``python -m bluefog_tpu.tools top`` — live fleet dashboard.

A curses-free refresh-loop view of a running gang: every interval it
polls each rank's telemetry endpoint (``/metrics`` + ``/healthz``,
served by ``utils/telemetry.start_http_server`` /
``BLUEFOG_TPU_TELEMETRY_PORT``) and renders, in one terminal frame,

  * per-rank health: status, step clock / async lag, deepest tx queue,
    straggler score, measured fused-step overlap (``!``-flagged when
    the measured-vs-modeled divergence crosses the link observatory's
    x3 alert threshold), SLO breaches;
  * the cluster link matrix: per-edge measured one-way delay, jitter and
    measured-vs-modeled divergence (the link observatory's
    ``bf_link_*`` gauges, MAX-merged across ranks exactly as the
    aggregate-snapshot collective merges gauges);
  * membership (epoch, active/suspect ranks) when the churn controller
    is live.

Endpoint discovery, in order of preference:

  --endpoints host:port,host:port,...
      Explicit metrics endpoints, one per process.

  --gang-dir <prefix> [--telemetry-base PORT]
      Read the PR-15 replicated gang directory
      (``BLUEFOG_TPU_GANG_DIR_PATH`` replicas, ``<prefix>.<proc>.json``)
      for the live processes' HOSTS; each proc's metrics port is
      ``--telemetry-base + proc`` (the ``bfrun --telemetry-port BASE``
      convention: rank r serves on BASE+r).

Plain HTTP + text rendering only — no curses, no jax, no live gang
membership of its own; safe to run from a laptop against any reachable
fleet.  ``--once`` (or ``--frames N``) renders and exits, which is also
what the smoke test drives.
"""

from __future__ import annotations

import argparse
import json
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

__all__ = ["parse_prometheus", "scrape", "render_frame", "main_top"]

_CLEAR = "\x1b[2J\x1b[H"


def parse_prometheus(text: str) -> Dict[str, float]:
    """Parse a ``/metrics`` exposition body into the rendered-key form
    the telemetry registry uses (``name{label="v",...}`` -> value)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        if not key:
            continue
        try:
            out[key] = float(val)
        except ValueError:
            continue
    return out


def scrape(endpoint: str, timeout: float = 2.0) \
        -> Tuple[Optional[Dict[str, float]], Optional[dict]]:
    """One poll of one rank: ``(metrics, health)``, either None on
    error — a dead rank renders as DOWN, it never kills the dashboard."""
    metrics = health = None
    try:
        with urllib.request.urlopen(f"http://{endpoint}/metrics",
                                    timeout=timeout) as r:
            metrics = parse_prometheus(r.read().decode("utf-8", "replace"))
    except (urllib.error.URLError, OSError, ValueError):
        pass
    try:
        with urllib.request.urlopen(f"http://{endpoint}/healthz",
                                    timeout=timeout) as r:
            health = json.loads(r.read().decode("utf-8", "replace"))
    except urllib.error.HTTPError as e:
        # /healthz serves 503 WITH the JSON body when degraded/stalled —
        # that body is the interesting one.
        try:
            health = json.loads(e.read().decode("utf-8", "replace"))
        except ValueError:
            pass
    except (urllib.error.URLError, OSError, ValueError):
        pass
    return metrics, health


def _gauge(metrics: Dict[str, float], name: str) -> Optional[float]:
    vals = [v for k, v in metrics.items()
            if k == name or k.startswith(name + "{")]
    return max(vals) if vals else None


def render_frame(polls: Dict[str, Tuple[Optional[Dict[str, float]],
                                        Optional[dict]]],
                 width: int = 100) -> str:
    """Render one dashboard frame from ``{endpoint: (metrics, health)}``
    polls.  Pure text — the function the smoke test asserts on."""
    from bluefog_tpu.utils import linkobs
    up = {ep: mh for ep, mh in polls.items() if mh[0] is not None}
    lines = [
        f"bluefog_tpu top — {time.strftime('%H:%M:%S')} — "
        f"{len(up)}/{len(polls)} endpoint(s) up",
        "=" * width,
    ]
    # -- membership (any live rank's view; epochs agree by consensus) ------
    member = next((h.get("membership") for _, h in up.values()
                   if h and h.get("membership")), None)
    if member:
        lines.append(
            f"membership: epoch {member.get('epoch')}, "
            f"{len(member.get('active_ranks', []))}/"
            f"{member.get('world_ranks', '?')} ranks active"
            + (f", suspects {member['suspect_ranks']}"
               if member.get("suspect_ranks") else ""))
    # -- per-rank table ----------------------------------------------------
    lines.append(f"{'endpoint':<22} {'status':<9} {'step':>7} "
                 f"{'lag':>5} {'queue':>6} {'straggler':>10} "
                 f"{'ovlp':>7} {'tune':<14} {'slo':<20}")
    lines.append("-" * width)
    for ep in sorted(polls):
        metrics, health = polls[ep]
        if metrics is None:
            lines.append(f"{ep:<22} {'DOWN':<9}")
            continue
        status = (health or {}).get("status", "?")
        a = (health or {}).get("async") or {}
        step = a.get("step", _gauge(metrics, "bf_async_step_lag") and "?")
        lag = a.get("step_lag")
        if lag is None:
            lag = _gauge(metrics, "bf_async_step_lag")
        q = (health or {}).get("win_tx_deepest_queue", {}).get("depth")
        if q is None:
            q = _gauge(metrics, "bf_win_tx_queue_depth")
        sc = (health or {}).get("straggler", {}).get("straggler_score")
        # Measured fused-step overlap (the in-program probes' gauge);
        # flagged, link-observatory style, when measurement and the
        # static model disagree past the x3 alert threshold in either
        # direction — a rank whose puts are NOT hiding where the
        # schedule preview says they should.
        ovlp = _gauge(metrics, "bf_fused_overlap_ratio")
        odiv = _gauge(metrics, "bf_fused_overlap_divergence_ratio")
        ovlp_txt = f"{ovlp:.2f}" if ovlp is not None else "-"
        if odiv is not None and \
                max(odiv, 1.0 / max(odiv, 1e-9)) > linkobs.DIVERGENCE_ALERT:
            ovlp_txt += "!"
        # Self-tuning control plane: "<epoch>:<last knob>", "!"-flagged
        # while a revert-on-regression probation window is open ("-" when
        # the tuner is off: no block, no column content).
        tb = (health or {}).get("tuner") or {}
        if tb:
            # Truncate BEFORE the probation flag: the "!" must survive a
            # long knob name in the 14-char cell.
            tune_txt = \
                f"{tb.get('epoch', 0)}:{tb.get('last_knob') or '-'}"[:13]
            if tb.get("probation"):
                tune_txt += "!"
        else:
            te = _gauge(metrics, "bf_tune_epoch")
            tune_txt = f"{te:g}" if te is not None else "-"
        slo = ((health or {}).get("links") or {}).get("slo", {})
        slo_txt = ("BREACH " + ",".join(slo["breached"])
                   if slo.get("breached")
                   else ("ok" if slo.get("rules") else "-"))
        lines.append(
            f"{ep:<22} {status:<9} "
            f"{step if step is not None else '-':>7} "
            f"{f'{lag:g}' if lag is not None else '-':>5} "
            f"{f'{q:g}' if q is not None else '-':>6} "
            f"{f'{sc:.2f}' if sc is not None else '-':>10} "
            f"{ovlp_txt:>7} "
            f"{tune_txt[:14]:<14} "
            f"{slo_txt[:20]:<20}")
    # -- link matrix (gauge-MAX merge: each edge lives on its receiver) ----
    merged = linkobs.merge_link_snapshots(
        [m for m, _ in up.values() if m])
    report = linkobs.report_from_snapshot(merged)
    lines.append("")
    if report.get("edges"):
        lines.append(
            f"link matrix ({len(report['edges'])} edge(s)) — "
            f"max divergence x"
            f"{report.get('max_divergence_ratio', 1.0):.2f}:")
        lines.append(f"  {'edge':<12} {'delay_us':>10} {'jitter_us':>10} "
                     f"{'divergence':>11}")
        hot = report.get("hot_edge")
        for r in report["edges"]:
            mark = " <- HOT" if hot and (r["src"], r["dst"]) == \
                (hot["src"], hot["dst"]) else ""
            edge = "{} -> {}".format(r["src"], r["dst"])
            lines.append(
                f"  {edge:<12} "
                f"{r.get('delay_us', 0.0):>10.1f} "
                f"{r.get('jitter_us', 0.0):>10.1f} "
                f"{r.get('divergence_ratio', 1.0):>11.3f}{mark}")
    else:
        lines.append("link matrix: no bf_link_* series yet "
                     "(BLUEFOG_TPU_LINK_OBS off, or no traced traffic)")
    # -- worst contribution age across the fleet ---------------------------
    ages = [(ep, s, a.get("stalest_sec"))
            for ep, (_, h) in up.items()
            for s, a in ((h or {}).get("contribution_age") or {}).items()
            if a.get("stalest_sec") is not None]
    if ages:
        ep, s, sec = max(ages, key=lambda t: t[2])
        lines.append(f"stalest contribution: src {s} at {ep} "
                     f"({sec:.3f}s)")
    lines.append("=" * width)
    return "\n".join(lines)


def _discover_endpoints(args) -> List[str]:
    if args.endpoints:
        return [e.strip() for e in args.endpoints.split(",") if e.strip()]
    if args.gang_dir:
        from bluefog_tpu.ops.gang import GangDirectory
        d = GangDirectory.load_any(args.gang_dir)
        eps = []
        for proc in (d.active or sorted(d.endpoints)):
            ep = d.endpoints.get(proc)
            if ep is None:
                continue
            host = ep.rsplit(":", 1)[0]
            eps.append(f"{host}:{args.telemetry_base + int(proc)}")
        if eps:
            return eps
        raise SystemExit("top: gang directory has no live endpoints")
    raise SystemExit(
        "top: need --endpoints host:port,... or --gang-dir <prefix> "
        "(with --telemetry-base matching bfrun --telemetry-port)")


def main_top(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.tools top",
        description="live fleet dashboard over /metrics + /healthz")
    p.add_argument("--endpoints", default=None,
                   help="comma-separated metrics endpoints (host:port)")
    p.add_argument("--gang-dir", default=None,
                   help="gang-directory replica prefix "
                        "(BLUEFOG_TPU_GANG_DIR_PATH) for host discovery")
    p.add_argument("--telemetry-base", type=int, default=9100,
                   help="metrics port base with --gang-dir: proc p serves "
                        "on base+p (bfrun --telemetry-port convention; "
                        "default 9100)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds (default 2)")
    p.add_argument("--frames", type=int, default=0,
                   help="render N frames then exit (0 = until Ctrl-C)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (= --frames 1)")
    p.add_argument("--plain", action="store_true",
                   help="never clear the screen between frames (logs, CI)")
    args = p.parse_args(argv)
    endpoints = _discover_endpoints(args)
    frames = 1 if args.once else args.frames
    n = 0
    try:
        while True:
            polls = {ep: scrape(ep) for ep in endpoints}
            frame = render_frame(polls)
            if not args.plain and frames != 1:
                print(_CLEAR, end="")
            print(frame, flush=True)
            n += 1
            if frames and n >= frames:
                return 0
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main_top())
