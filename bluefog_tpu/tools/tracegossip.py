"""trace-gossip: merge per-rank flight-recorder dumps into one timeline.

The flight recorder (``utils/flightrec.py`` / ``bf_rec_*`` in
``native/src/winsvc.cc``) gives every rank a black box of transport
events; the wire trace tags (``BLUEFOG_TPU_TRACE_SAMPLE``) give a
sampled subset of gossip messages a cross-rank identity
``(src_rank, seq)``.  This module joins the two:

  python -m bluefog_tpu.tools trace-gossip <prefix> [-o merged.json]

reads every ``<prefix>.<rank>.bin`` dump, aligns the ranks' monotonic
event clocks onto the unix-time axis via each dump's embedded clock
anchor (the PR-3 trace-merge convention: one (monotonic_us, unix_us)
pair per file), and writes a chrome trace with

  * one process lane per rank, a tx thread (enqueue/flush/sendmsg) and
    an rx thread (drain/decode/fold/commit) each;
  * a FLOW ARROW per matched trace tag — from the sender's enqueue
    event to the receiver's decode — so one put can be followed across
    the rank boundary in ``chrome://tracing`` / Perfetto;

and prints the per-edge one-way-delay table (p50/p99 of enqueue→decode
latency per directed (src → dst-rank) edge — NTP-grade across hosts,
exact for same-host gangs, since CLOCK_MONOTONIC is per boot).

Everything here is pure host math over the dump files: no jax, no mesh,
no live gang — it runs on whatever survived a chaos kill.
"""

from __future__ import annotations

import glob
import json
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from bluefog_tpu.utils import flightrec

__all__ = ["dump_files", "load_dumps", "edge_delays", "delay_table",
           "edge_delay_records", "merge_gossip"]

# Sender-side chain start and receiver-side chain end of one tagged
# message, for flow arrows and the delay table.
_TX_TYPES = (flightrec.ENQUEUE, flightrec.FLUSH, flightrec.SENDMSG)


def dump_files(prefix: str) -> Dict[int, str]:
    """``{rank: path}`` of the flight-recorder dumps written under
    ``prefix`` (the naming contract: ``<prefix>.<rank>.bin``)."""
    out: Dict[int, str] = {}
    for path in glob.glob(glob.escape(prefix) + ".*.bin"):
        m = re.fullmatch(re.escape(prefix) + r"\.(\d+)\.bin", path)
        if m:
            out[int(m.group(1))] = path
    return dict(sorted(out.items()))


def load_dumps(prefix: str) -> List[dict]:
    """Load every per-rank dump: ``[{rank, offset_us, events}, ...]``
    with ``offset_us`` the µs to add to an event's monotonic timestamp
    to land on the unix-time axis (the dump's clock anchor)."""
    files = dump_files(prefix)
    if not files:
        raise FileNotFoundError(
            f"no flight-recorder dumps match {prefix}.<rank>.bin")
    out = []
    for rank, path in files.items():
        header, events = flightrec.load(path)
        out.append({"rank": rank, "path": path,
                    "offset_us": header["unix_us"] - header["mono_us"],
                    "events": events})
    return out


def _tag_endpoints(dumps: List[dict]):
    """Per matched trace tag ``(src_rank, seq)``: the sender's first tx
    event and the receiver's first rx event, each as ``(dump, index)``.
    Unmatched tags (the other side's ring wrapped past them, or the peer
    died before dumping) are simply absent — the black box reports what
    it has."""
    tx: Dict[Tuple[int, int], Tuple[dict, int]] = {}
    rx: Dict[Tuple[int, int], Tuple[dict, int]] = {}
    for d in dumps:
        ev = d["events"]
        tagged = np.nonzero(ev["seq"])[0]
        for i in tagged:
            key = (int(ev["src"][i]), int(ev["seq"][i]))
            et = int(ev["etype"][i])
            # Only ENQUEUE (tx) and DECODE/FOLD/COMMIT (rx) events carry
            # a TRACE seq; on FLUSH/SENDMSG frame events the seq field is
            # the frame's message count, never a tag.
            if et == flightrec.ENQUEUE:
                if key not in tx or ev["t_us"][i] < \
                        tx[key][0]["events"]["t_us"][tx[key][1]]:
                    tx[key] = (d, int(i))
            elif et == flightrec.DECODE:
                if key not in rx or ev["t_us"][i] < \
                        rx[key][0]["events"]["t_us"][rx[key][1]]:
                    rx[key] = (d, int(i))
            elif et in (flightrec.FOLD, flightrec.COMMIT) \
                    and key not in rx:
                rx[key] = (d, int(i))
    return tx, rx


def edge_delays(dumps: List[dict]) -> Dict[Tuple[int, int], np.ndarray]:
    """One-way delays per directed edge: ``{(src_rank, dst_rank):
    delays_us}`` from matched (sender enqueue → receiver decode) trace
    tags, wall-aligned through each dump's clock anchor."""
    tx, rx = _tag_endpoints(dumps)
    per_edge: Dict[Tuple[int, int], List[float]] = {}
    for key, (sd, si) in tx.items():
        hit = rx.get(key)
        if hit is None:
            continue
        rd, ri = hit
        send_wall = int(sd["events"]["t_us"][si]) + sd["offset_us"]
        recv_wall = int(rd["events"]["t_us"][ri]) + rd["offset_us"]
        edge = (key[0], rd["rank"])
        per_edge.setdefault(edge, []).append(recv_wall - send_wall)
    return {e: np.asarray(v, dtype=np.float64)
            for e, v in sorted(per_edge.items())}


def delay_table(delays: Dict[Tuple[int, int], np.ndarray]) -> str:
    """Per-edge one-way-delay p50/p99 text table (ms)."""
    if not delays:
        return ("trace-gossip: no matched trace tags across the dumps "
                "(was BLUEFOG_TPU_TRACE_SAMPLE set on the senders?)")
    header = (f"{'edge':<14} {'tags':>6} {'p50_ms':>9} {'p99_ms':>9} "
              f"{'max_ms':>9}")
    lines = [header, "-" * len(header)]
    for (src, dst), d in delays.items():
        p50, p99 = np.percentile(d, [50, 99])
        lines.append(f"{f'{src} -> {dst}':<14} {len(d):>6} "
                     f"{p50 / 1e3:>9.3f} {p99 / 1e3:>9.3f} "
                     f"{d.max() / 1e3:>9.3f}")
    return "\n".join(lines)


def edge_delay_records(delays: Dict[Tuple[int, int], np.ndarray]) \
        -> List[dict]:
    """The delay table as machine-readable rows (``--json``): one dict
    per directed edge, same edges and the same ms percentiles as
    :func:`delay_table` — what CI and ``bench_comm.py`` diff against the
    link observatory's ONLINE estimates."""
    out = []
    for (src, dst), d in delays.items():
        p50, p99 = np.percentile(d, [50, 99])
        out.append({"src": int(src), "dst": int(dst), "tags": int(len(d)),
                    "p50_ms": float(p50 / 1e3), "p99_ms": float(p99 / 1e3),
                    "max_ms": float(d.max() / 1e3)})
    return out


def merge_gossip(prefix: str, out_path: Optional[str] = None,
                 dumps: Optional[List[dict]] = None) -> Tuple[str, dict]:
    """Merge the dumps under ``prefix`` into one chrome trace with a
    process lane per rank and cross-rank flow arrows per matched trace
    tag.  Returns ``(out_path, stats)``."""
    if dumps is None:
        dumps = load_dumps(prefix)
    tx, rx = _tag_endpoints(dumps)
    flows = {k for k in tx if k in rx}
    # Rebase so t=0 is the earliest wall-aligned event (readable numbers).
    starts = [int(d["events"]["t_us"].min()) + d["offset_us"]
              for d in dumps if len(d["events"])]
    base = min(starts, default=0)
    merged: List[dict] = []
    for d in dumps:
        rank = d["rank"]
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0, "ts": 0, "args": {"name": f"rank {rank}"}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "tid": 0, "ts": 0,
                       "args": {"sort_index": rank}})
        for tid, label in ((0, "tx"), (1, "rx")):
            merged.append({"name": "thread_name", "ph": "M", "pid": rank,
                           "tid": tid, "ts": 0, "args": {"name": label}})
        ev = d["events"]
        for i in range(len(ev)):
            et = int(ev["etype"][i])
            ts = int(ev["t_us"][i]) + d["offset_us"] - base
            tid = 0 if et in _TX_TYPES else 1
            name = ev["name"][i].split(b"\0", 1)[0].decode(
                "utf-8", "replace")
            ename = flightrec.ETYPE_NAMES.get(et, str(et))
            merged.append({
                "name": f"{ename} {name}".rstrip(), "ph": "X", "ts": ts,
                "dur": 1, "pid": rank, "tid": tid, "cat": "gossip",
                "args": {"op": int(ev["op"][i]), "src": int(ev["src"][i]),
                         "dst": int(ev["dst"][i]),
                         "seq": int(ev["seq"][i]),
                         "stripe": int(ev["stripe"][i]),
                         "bytes": int(ev["len"][i])}})
            key = (int(ev["src"][i]), int(ev["seq"][i]))
            if key in flows:
                # Flow arrow endpoints bind to the co-timed slice above
                # (identity match: the dicts are the loaded dump objects).
                if tx[key][0] is d and tx[key][1] == i:
                    merged.append({"name": "gossip", "cat": "flow",
                                   "ph": "s", "id": (key[0] << 32)
                                   | key[1], "pid": rank, "tid": tid,
                                   "ts": ts})
                elif rx[key][0] is d and rx[key][1] == i:
                    merged.append({"name": "gossip", "cat": "flow",
                                   "ph": "f", "bp": "e",
                                   "id": (key[0] << 32) | key[1],
                                   "pid": rank, "tid": tid, "ts": ts})
    if out_path is None:
        out_path = prefix + ".merged.json"
    with open(out_path, "w") as f:
        json.dump(merged, f)
    stats = {
        "ranks": [d["rank"] for d in dumps],
        "events": int(sum(len(d["events"]) for d in dumps)),
        "tags_sent": len(tx),
        "flows_matched": len(flows),
    }
    return out_path, stats


def main_trace_gossip(prefix: str, out_path: Optional[str] = None,
                      as_json: bool = False) -> int:
    dumps = load_dumps(prefix)
    out, stats = merge_gossip(prefix, out_path, dumps=dumps)
    delays = edge_delays(dumps)
    if as_json:
        # Machine-readable mode: stdout is EXACTLY one JSON document
        # (json.loads round-trips the whole output), same edges as the
        # text table.
        print(json.dumps({"trace": out, "stats": stats,
                          "edges": edge_delay_records(delays)},
                         indent=2, sort_keys=True))
        return 0
    print(f"trace-gossip: wrote {out} ({stats['events']} events, "
          f"{len(stats['ranks'])} rank lane(s), "
          f"{stats['flows_matched']}/{stats['tags_sent']} trace tag(s) "
          "matched into flow arrows)")
    print()
    print(delay_table(delays))
    return 0
