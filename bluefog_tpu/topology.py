"""Virtual-topology library for decentralized averaging on TPU meshes.

This module provides the static graph generators, weight-extraction helpers and
dynamic (per-iteration) topology schedules that drive every neighbor-averaging
collective in :mod:`bluefog_tpu`.  It covers the full generator inventory of the
reference framework (see ``bluefog/common/topology_util.py`` in BlueFog:
ExponentialTwoGraph :66, ExponentialGraph :99, SymmetricExponentialGraph :128,
MeshGrid2DGraph :160, StarGraph :214, RingGraph :240, FullyConnectedGraph :284,
dynamic generators :315-554) while adding a TPU-first concept the reference does
not have: a *phase table* (:func:`dynamic_phase_table`,
:class:`bluefog_tpu.ops.schedule.CommSchedule`) — a static, precomputed
description of every per-step communication pattern, so that dynamic topologies
compile once under ``jax.jit`` (``lax.switch`` over phases) instead of being
re-negotiated every step by a coordinator thread.

Conventions
-----------
A topology is a weighted ``networkx.DiGraph`` over ranks ``0..n-1`` whose
adjacency matrix ``W`` is read as ``W[src, dst] = weight``.  Averaging steps
compute ``x_dst <- sum_src W[src, dst] * x_src``; generators produce
doubly-stochastic (or at least column-stochastic from the receiver's point of
view) matrices so consensus preserves the global mean.  A nonzero diagonal
entry is the rank's self-weight.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import networkx as nx
import numpy as np

__all__ = [
    "IsTopologyEquivalent",
    "IsRegularGraph",
    "GetRecvWeights",
    "GetSendWeights",
    "ExponentialTwoGraph",
    "ExponentialGraph",
    "SymmetricExponentialGraph",
    "MeshGrid2DGraph",
    "StarGraph",
    "RingGraph",
    "FullyConnectedGraph",
    "RandomRegularGraph",
    "GetDynamicOnePeerSendRecvRanks",
    "GetExp2DynamicSendRecvMachineRanks",
    "GetInnerOuterRingDynamicSendRecvRanks",
    "GetInnerOuterExpo2DynamicSendRecvRanks",
    "weight_matrix",
    "from_weight_matrix",
    "in_neighbor_ranks",
    "out_neighbor_ranks",
    "DynamicPhase",
    "dynamic_phase_table",
    "one_peer_exp2_phases",
    "HierarchicalTopology",
    "hierarchical_two_level",
]


# ---------------------------------------------------------------------------
# Matrix <-> graph plumbing
# ---------------------------------------------------------------------------

def weight_matrix(topo: nx.DiGraph) -> np.ndarray:
    """Dense ``W[src, dst]`` weight matrix of a topology."""
    return nx.to_numpy_array(topo, nodelist=range(topo.number_of_nodes()))


def from_weight_matrix(w: np.ndarray) -> nx.DiGraph:
    """Build a topology from a dense ``W[src, dst]`` weight matrix."""
    w = np.asarray(w, dtype=float)
    assert w.ndim == 2 and w.shape[0] == w.shape[1], "weight matrix must be square"
    return nx.from_numpy_array(w, create_using=nx.DiGraph)


def _circulant(first_row: np.ndarray) -> nx.DiGraph:
    """Topology whose row ``i`` is ``first_row`` rotated right by ``i``.

    Circulant weight matrices are doubly stochastic whenever ``first_row`` sums
    to one, which is why every shift-structured generator below funnels through
    here.
    """
    n = len(first_row)
    rows = [np.roll(first_row, shift) for shift in range(n)]
    return from_weight_matrix(np.stack(rows))


def in_neighbor_ranks(topo: nx.DiGraph, rank: int) -> List[int]:
    """Ranks with an edge into ``rank`` (excluding the self-loop)."""
    return sorted(r for r in topo.predecessors(rank) if r != rank)


def out_neighbor_ranks(topo: nx.DiGraph, rank: int) -> List[int]:
    """Ranks that ``rank`` has an edge to (excluding the self-loop)."""
    return sorted(r for r in topo.successors(rank) if r != rank)


# ---------------------------------------------------------------------------
# Predicates and weight extraction (API parity: topology_util.py:23-63,306)
# ---------------------------------------------------------------------------

def IsTopologyEquivalent(topo1: Optional[nx.DiGraph],
                         topo2: Optional[nx.DiGraph]) -> bool:
    """True iff two topologies have identical adjacency/weight matrices.

    Deliberately *not* an isomorphism check — rank identity matters for
    communication schedules (matches reference semantics,
    ``topology_util.py:23-37``).
    """
    if topo1 is None or topo2 is None:
        return False
    if topo1.number_of_nodes() != topo2.number_of_nodes():
        return False
    if topo1.number_of_edges() != topo2.number_of_edges():
        return False
    return bool(np.array_equal(weight_matrix(topo1), weight_matrix(topo2)))


def IsRegularGraph(topo: nx.DiGraph) -> bool:
    """True iff every rank has the same total (in+out) degree."""
    degrees = {topo.degree(r) for r in range(topo.number_of_nodes())}
    return len(degrees) == 1


def GetRecvWeights(topo: nx.DiGraph, rank: int) -> Tuple[float, Dict[int, float]]:
    """``(self_weight, {src_rank: weight})`` used when *receiving* updates."""
    w = weight_matrix(topo)
    neighbor_weights = {src: w[src, rank] for src in topo.predecessors(rank)
                        if src != rank}
    self_weight = float(w[rank, rank]) if topo.has_edge(rank, rank) else 0.0
    return self_weight, neighbor_weights


def GetSendWeights(topo: nx.DiGraph, rank: int) -> Tuple[float, Dict[int, float]]:
    """``(self_weight, {dst_rank: weight})`` used when *sending* updates."""
    w = weight_matrix(topo)
    neighbor_weights = {dst: w[rank, dst] for dst in topo.successors(rank)
                        if dst != rank}
    self_weight = float(w[rank, rank]) if topo.has_edge(rank, rank) else 0.0
    return self_weight, neighbor_weights


# ---------------------------------------------------------------------------
# Static generators
# ---------------------------------------------------------------------------

def _power_offsets(size: int, base: int) -> List[int]:
    """Offsets ``{base**k} < size`` (exact integer arithmetic, no float log)."""
    offsets, p = [], 1
    while p < size:
        offsets.append(p)
        p *= base
    return offsets


def ExponentialTwoGraph(size: int) -> nx.DiGraph:
    """Directed circulant where rank ``i`` sends to ``i + 2**k (mod size)``.

    The flagship BlueFog topology (reference ``topology_util.py:66-87``): in-
    and out-degree are ``log2(size)``-ish, spectral gap is good, and every
    round of the dynamic one-peer variant is a single cyclic shift — on TPU a
    single ``lax.ppermute``.
    """
    assert size > 0
    row = np.zeros(size)
    row[0] = 1.0
    for d in _power_offsets(size, 2):
        row[d] = 1.0
    return _circulant(row / row.sum())


def ExponentialGraph(size: int, base: int = 2) -> nx.DiGraph:
    """Circulant with connections at offsets ``base**k`` (reference :99-125)."""
    assert size > 0
    row = np.zeros(size)
    row[0] = 1.0
    for d in _power_offsets(size, base):
        row[d] = 1.0
    return _circulant(row / row.sum())


def SymmetricExponentialGraph(size: int, base: int = 4) -> nx.DiGraph:
    """Circulant with offsets ``base**k`` mirrored around ``size//2``.

    Offset ``d`` participates iff ``min(d, size-d)`` is a power of ``base``
    (reference ``topology_util.py:128-157``).
    """
    assert size > 0
    powers = set(_power_offsets(size, base))
    row = np.zeros(size)
    row[0] = 1.0
    for d in range(1, size):
        folded = d if d <= size // 2 else size - d
        if folded in powers:
            row[d] = 1.0
    return _circulant(row / row.sum())


def MeshGrid2DGraph(size: int, shape: Optional[Tuple[int, int]] = None) -> nx.DiGraph:
    """2-D grid with Metropolis–Hastings weights (reference :160-211).

    Edge weight is ``1 / max(|N_i|, |N_j|)`` with neighborhoods counted
    *including* self; the diagonal absorbs the slack so each row sums to one.
    When ``shape`` is omitted the grid is the most-square factorization, rows
    <= cols; prime sizes degrade to a path.
    """
    assert size > 0
    if shape is None:
        nrow = int(math.isqrt(size))
        while size % nrow != 0:
            nrow -= 1
        shape = (nrow, size // nrow)
    nrow, ncol = shape
    assert nrow * ncol == size, "shape does not match size"

    adj = np.zeros((size, size), dtype=bool)
    for i in range(size):
        r, c = divmod(i, ncol)
        if c + 1 < ncol:
            adj[i, i + 1] = adj[i + 1, i] = True
        if r + 1 < nrow:
            adj[i, i + ncol] = adj[i + ncol, i] = True

    nbhd_size = adj.sum(axis=1) + 1  # |N_i| including self
    w = np.zeros((size, size))
    for i in range(size):
        for j in np.nonzero(adj[i])[0]:
            w[i, j] = 1.0 / max(nbhd_size[i], nbhd_size[j])
        w[i, i] = 1.0 - w[i].sum()
    return from_weight_matrix(w)


def StarGraph(size: int, center_rank: int = 0) -> nx.DiGraph:
    """Bidirectional star through ``center_rank`` (reference :214-237).

    Leaves keep ``1 - 1/size`` self-weight and exchange ``1/size`` with the
    center; the center row is uniform ``1/size``.
    """
    assert size > 0
    w = np.zeros((size, size))
    np.fill_diagonal(w, 1.0 - 1.0 / size)
    w[center_rank, :] = 1.0 / size
    w[:, center_rank] = 1.0 / size
    w[center_rank, center_rank] = 1.0 / size
    return from_weight_matrix(w)


def RingGraph(size: int, connect_style: int = 0) -> nx.DiGraph:
    """Ring topology (reference :240-281).

    ``connect_style``: 0 = bidirectional (1/3 self, 1/3 each side),
    1 = left only (send to ``i-1``), 2 = right only (send to ``i+1``).
    """
    assert size > 0
    assert 0 <= connect_style <= 2, "connect_style must be 0 (bi), 1 (left) or 2 (right)"
    if size == 1:
        return from_weight_matrix(np.ones((1, 1)))
    if size == 2:
        return from_weight_matrix(np.full((2, 2), 0.5))
    row = np.zeros(size)
    if connect_style == 0:
        row[0] = row[1] = row[-1] = 1.0 / 3.0
    elif connect_style == 1:
        row[0] = row[-1] = 0.5
    else:
        row[0] = row[1] = 0.5
    return _circulant(row)


def FullyConnectedGraph(size: int) -> nx.DiGraph:
    """All-to-all with uniform ``1/size`` weights (reference :284-303)."""
    assert size > 0
    return from_weight_matrix(np.full((size, size), 1.0 / size))


def RandomRegularGraph(size: int, degree: int = 4,
                       seed: int = 0) -> nx.DiGraph:
    """Random ``degree``-regular undirected graph as a bidirectional
    topology with uniform ``1/(degree+1)`` weights (doubly stochastic).

    Random-regular graphs are expanders with high probability — near-Exp2
    spectral gap at constant degree — but carry NO shift structure: their
    edges scatter across ~``size`` cyclic distance classes, which makes
    them the stress topology for the schedule optimizer
    (``ops/schedule_opt.py`` repacks them from ~``size`` naive ppermute
    rounds down to exactly ``degree``).  Deterministic in ``seed`` so every
    rank builds the identical graph.
    """
    assert size > 0 and 0 < degree < size, "need 0 < degree < size"
    assert (size * degree) % 2 == 0, "size * degree must be even"
    g = nx.random_regular_graph(degree, size, seed=seed)
    w = np.zeros((size, size))
    share = 1.0 / (degree + 1.0)
    for u, v in g.edges():
        w[u, v] = w[v, u] = share
    np.fill_diagonal(w, share)
    return from_weight_matrix(w)


# ---------------------------------------------------------------------------
# Dynamic (one-peer-per-iteration) schedules
# ---------------------------------------------------------------------------
#
# The reference exposes these as infinite Python iterators consumed rank-by-
# rank (topology_util.py:315-554).  We keep those iterators for API parity but
# derive them from *pure functions of the step index*, which is what the TPU
# path actually consumes: a static table of per-phase global permutations that
# `ops.schedule` turns into `lax.ppermute` source-target pairs selected by
# `lax.switch` — no per-step host negotiation, no recompilation.


@dataclass(frozen=True)
class DynamicPhase:
    """One phase of a periodic dynamic topology.

    ``send_to[i]`` is the rank that ``i`` sends to in this phase (or ``-1`` if
    ``i`` stays silent).  Receives are implied: ``j`` receives from every ``i``
    with ``send_to[i] == j``.
    """
    send_to: Tuple[int, ...]

    @property
    def pairs(self) -> List[Tuple[int, int]]:
        return [(src, dst) for src, dst in enumerate(self.send_to) if dst >= 0]

    def recv_from(self, rank: int) -> List[int]:
        return [src for src, dst in enumerate(self.send_to) if dst == rank]


def _sorted_clockwise_out_neighbors(topo: nx.DiGraph) -> List[List[int]]:
    """Per-rank out-neighbors ordered by clockwise distance, self excluded."""
    n = topo.number_of_nodes()
    table = []
    for r in range(n):
        nbrs = [s for s in topo.successors(r) if s != r]
        nbrs.sort(key=lambda s: (s - r) % n)
        table.append(nbrs)
    return table


def dynamic_phase_table(topo: nx.DiGraph,
                        max_phases: int = 1024) -> List[DynamicPhase]:
    """Static phase table for the one-peer dynamic walk over ``topo``.

    Phase ``p``: rank ``i`` sends to its ``p % outdeg(i)``-th clockwise
    out-neighbor — the same walk as :func:`GetDynamicOnePeerSendRecvRanks`,
    with which it agrees exactly (the table length is the full period
    ``lcm(outdeg(i))``; step ``t`` uses phase ``t % len(table)``).  Raises
    when the period exceeds ``max_phases`` rather than silently truncating —
    a truncated table would diverge from the iterator after one period.
    """
    n = topo.number_of_nodes()
    nbrs = _sorted_clockwise_out_neighbors(topo)
    degs = [max(len(x), 1) for x in nbrs]
    period = 1
    for d in degs:
        period = math.lcm(period, d)
    if period > max_phases:
        raise ValueError(
            f"dynamic phase period lcm(outdegrees)={period} exceeds "
            f"max_phases={max_phases}; use a more regular topology or raise "
            "max_phases explicitly")
    phases = []
    for p in range(period):
        send_to = tuple(nbrs[i][p % degs[i]] if nbrs[i] else -1 for i in range(n))
        phases.append(DynamicPhase(send_to))
    return phases


def one_peer_exp2_phases(size: int) -> List[DynamicPhase]:
    """Phase table for dynamic one-peer Exponential-2: phase ``k`` is the pure
    cyclic shift by ``2**k``.  Each phase is exactly one ``lax.ppermute``."""
    offsets = _power_offsets(size, 2) or [0]
    return [DynamicPhase(tuple((i + d) % size for i in range(size)))
            for d in offsets]


def GetDynamicOnePeerSendRecvRanks(
        topo: nx.DiGraph, self_rank: int) -> Iterator[Tuple[List[int], List[int]]]:
    """Per-step ``([send_rank], recv_ranks)`` for the one-peer dynamic walk.

    API parity with reference ``topology_util.py:315-357``; backed by the same
    phase table the jitted path uses, so eager and compiled schedules agree.
    """
    nbrs = _sorted_clockwise_out_neighbors(topo)
    degs = [max(len(x), 1) for x in nbrs]
    n = topo.number_of_nodes()
    step = 0
    while True:
        # A rank without out-edges sits the round out (phase table emits -1)
        sends = [nbrs[self_rank][step % degs[self_rank]]] if nbrs[self_rank] else []
        recvs = [other for other in range(n)
                 if other != self_rank and nbrs[other]
                 and nbrs[other][step % degs[other]] == self_rank]
        yield sends, recvs
        step += 1


def GetExp2DynamicSendRecvMachineRanks(
        world_size: int, local_size: int, self_rank: int, local_rank: int,
) -> Iterator[Tuple[List[int], List[int]]]:
    """Machine-level dynamic Exp-2 walk for hierarchical averaging.

    Yields ``([send_machine_id], [recv_machine_id])`` per step (reference
    ``topology_util.py:360-396``).  Homogeneous placement required.
    """
    assert self_rank % local_size == local_rank, "homogeneous placement required"
    assert world_size % local_size == 0, "homogeneous placement required"
    assert world_size > local_size, "needs at least two machines"
    machine_id = self_rank // local_size
    num_machines = world_size // local_size
    num_offsets = int(np.log2(num_machines - 1)) + 1 if num_machines > 1 else 1
    step = 0
    while True:
        dist = 2 ** (step % num_offsets)
        yield [(machine_id + dist) % num_machines], [(machine_id - dist) % num_machines]
        step += 1


def _inner_outer_step(num_machines: int, nodes_per_machine: int, self_rank: int,
                      step: int, inner_dist_fn, outer_dist_fn) -> Tuple[int, int]:
    """Shared skeleton of the inner/outer dynamic walks.

    One designated local rank per step talks across machines; all others walk
    inside their machine, skipping over the outgoing rank.
    """
    machine_id, local_id = divmod(self_rank, nodes_per_machine)
    outgoing_local = step % nodes_per_machine

    if local_id == outgoing_local:
        d = outer_dist_fn(step)
        send = ((machine_id + d) % num_machines) * nodes_per_machine + local_id
        recv = ((machine_id - d) % num_machines) * nodes_per_machine + local_id
        return send, recv

    fwd = inner_dist_fn(step)
    if fwd >= (outgoing_local - local_id) % nodes_per_machine:
        fwd += 1
    send = machine_id * nodes_per_machine + (local_id + fwd) % nodes_per_machine
    bwd = inner_dist_fn(step)
    if bwd >= (local_id - outgoing_local) % nodes_per_machine:
        bwd += 1
    recv = machine_id * nodes_per_machine + (local_id - bwd) % nodes_per_machine
    return send, recv


def GetInnerOuterRingDynamicSendRecvRanks(
        world_size: int, local_size: int, self_rank: int,
) -> Iterator[Tuple[List[int], List[int]]]:
    """Inner-ring / outer-ring dynamic walk (reference :399-463).

    Each step one local rank per machine hops to the next machine's same local
    rank; everyone else walks a ring inside the machine that detours around
    the outgoing rank.
    """
    assert world_size % local_size == 0, "homogeneous placement required"
    assert local_size > 2, "needs more than 2 ranks per machine"
    num_machines = world_size // local_size
    step = 0
    while True:
        send, recv = _inner_outer_step(
            num_machines, local_size, self_rank, step,
            inner_dist_fn=lambda _s: 1, outer_dist_fn=lambda _s: 1)
        yield [send], [recv]
        step += 1


def GetInnerOuterExpo2DynamicSendRecvRanks(
        world_size: int, local_size: int, self_rank: int,
) -> Iterator[Tuple[List[int], List[int]]]:
    """Inner-Exp2 / outer-Exp2 dynamic walk — the recommended production
    topology for multi-host training (reference :466-554)."""
    assert world_size % local_size == 0, "homogeneous placement required"
    assert local_size > 2, "needs more than 2 ranks per machine"
    num_machines = world_size // local_size
    outer_n = int(np.log2(num_machines - 1)) + 1 if num_machines > 1 else 1
    inner_n = 1 if local_size == 2 else int(np.log2(local_size - 2)) + 1
    step = 0
    while True:
        send, recv = _inner_outer_step(
            num_machines, local_size, self_rank, step,
            inner_dist_fn=lambda s: 2 ** (s % inner_n),
            outer_dist_fn=lambda s: 2 ** (s % outer_n))
        yield [send], [recv]
        step += 1


# ---------------------------------------------------------------------------
# Hierarchical two-level topology (dense ICI inner x sparse DCN outer)
# ---------------------------------------------------------------------------
#
# TPU pods are two networks in one: cheap dense ICI within a slice, scarce
# DCN links between slices (ops/placement.py models the gap as
# ``dcn_link_cost`` ~ 4x an ICI hop).  The legacy inner/outer dynamic walks
# above approximate the right decomposition but burn one designated rank per
# machine per step on DCN; the HiCCL-style composition below instead runs
# the FULL dense topology inside every slice every step and a one-peer
# dynamic walk *between* slices on its own cadence, with its own
# compression — the two levels priced and executed separately
# (``basics.hierarchical_gossip``).


@dataclass(frozen=True)
class HierarchicalTopology:
    """Two-level gossip topology artifact.

    ``inner``        — dense intra-slice topology over ``slice_size`` local
                       ranks (doubly-stochastic weight matrix; executed
                       over the ICI / LOCAL mesh axis), applied identically
                       inside every slice every step.
    ``outer_phases`` — one-peer dynamic walk over ``n_slices`` slices: each
                       phase is a full slice permutation (cyclic shift);
                       rank ``(m, i)`` exchanges with rank ``(m', i)`` of
                       the peer slice over the DCN level.
    ``outer_every``  — cadence ``k``: the outer level communicates only on
                       steps with ``step % k == 0``; other steps run the
                       inner level alone.
    ``outer_self_weight`` — per-OUTER-STEP self weight ``theta_k`` of the
                       sparse exchange (``x' = theta_k*x + (1-theta_k)*
                       x_peer`` per coordinate).  Built cadence-corrected
                       by :func:`hierarchical_two_level`: the requested
                       cadence-1 weight ``theta`` is raised to
                       ``theta**k`` so one cadence-``k`` exchange carries
                       the outer mixing mass of ``k`` cadence-1 exchanges.

    Every per-step operator — inner-only or inner-then-outer — is doubly
    stochastic (the inner matrix is doubly stochastic per slice and the
    outer is a convex combination of the identity and a permutation), so
    the ``k``-step effective operator is doubly stochastic too: cadence
    changes staleness, never the preserved global mean.
    """
    n: int
    n_slices: int
    slice_size: int
    inner: nx.DiGraph
    outer_phases: Tuple[DynamicPhase, ...]
    outer_every: int = 1
    outer_self_weight: float = 0.5
    inner_kind: str = "exp2"
    outer_kind: str = "exp2"

    # -- step policy --------------------------------------------------------

    @property
    def period(self) -> int:
        """Full schedule period in training steps."""
        return self.outer_every * max(len(self.outer_phases), 1)

    def is_outer_step(self, step: int) -> bool:
        return step % self.outer_every == 0

    def outer_phase_index(self, step: int, sweep_len: int = 1) -> int:
        """Phase of the outer walk at ``step`` (an outer step).

        ``sweep_len > 1`` (sparse outer compression with ``sweep_len``
        rotating index blocks) holds each phase for a full block sweep so
        every coordinate sees every phase — otherwise a block count
        sharing a factor with the phase count would pin some coordinates
        to a single shift distance forever."""
        outer_step = step // self.outer_every
        return (outer_step // max(sweep_len, 1)) % max(
            len(self.outer_phases), 1)

    # -- weight matrices -----------------------------------------------------

    def inner_weight_matrix(self) -> np.ndarray:
        """(slice_size, slice_size) doubly-stochastic inner matrix."""
        return weight_matrix(self.inner)

    def inner_full_matrix(self) -> np.ndarray:
        """(n, n) block-diagonal matrix applying ``inner`` in every slice."""
        return np.kron(np.eye(self.n_slices), self.inner_weight_matrix())

    def outer_slice_matrix(self, phase: int) -> np.ndarray:
        """(n_slices, n_slices) matrix of one outer phase:
        ``theta_k * I + (1 - theta_k) * P_shift`` — doubly stochastic for
        any self weight (convex combination of permutations)."""
        th = self.outer_self_weight
        w = np.eye(self.n_slices) * th
        for src, dst in self.outer_phases[phase].pairs:
            w[src, dst] += 1.0 - th
        return w

    def outer_full_matrix(self, phase: int) -> np.ndarray:
        """(n, n) outer matrix: the slice walk lifted to ranks (rank
        ``(m, i)`` pairs with the SAME local index ``i`` of the peer
        slice)."""
        return np.kron(self.outer_slice_matrix(phase),
                       np.eye(self.slice_size))

    def effective_weight_matrix(self, step: int) -> np.ndarray:
        """(n, n) effective operator of one step in the module-wide
        ``W[src, dst]`` convention: inner first, then (on outer steps) the
        outer exchange — ``x' = W_outer^T (W_inner^T x)``, i.e.
        ``W_eff = W_inner @ W_outer``."""
        w = self.inner_full_matrix()
        if self.outer_phases and self.is_outer_step(step):
            # A single-slice topology has no outer level: every step is
            # the inner operator alone.
            w = w @ self.outer_full_matrix(self.outer_phase_index(step))
        return w

    def product_topology(self, step: int = 0) -> nx.DiGraph:
        """The flat single-level topology equivalent to one hierarchical
        step — the equivalence-test oracle: executing the dense,
        uncompressed, cadence-1 hierarchical mode must match flat
        ``neighbor_allreduce`` over this graph to fp-reassociation
        tolerance."""
        return from_weight_matrix(self.effective_weight_matrix(step))

    def dcn_edges_per_outer_step(self) -> int:
        """Directed inter-slice edges of one outer step (each rank talks
        to exactly one peer rank in another slice)."""
        return self.n if self.n_slices > 1 else 0

    def ici_edges_per_step(self) -> int:
        """Directed intra-slice edges of one step: the inner topology's
        off-diagonal edge count, replicated in every slice — the ONE
        place the wire accounting (telemetry, BENCH json, schedule-dump)
        derives the dense level's per-step rows from."""
        w = self.inner_weight_matrix()
        off = w.copy()
        np.fill_diagonal(off, 0.0)
        return int((off != 0).sum()) * self.n_slices


def _outer_phase_table(n_slices: int, kind: str) -> Tuple[DynamicPhase, ...]:
    if n_slices <= 1:
        return ()
    if kind == "ring":
        return (DynamicPhase(tuple((m + 1) % n_slices
                                   for m in range(n_slices))),)
    if kind == "exp2":
        return tuple(one_peer_exp2_phases(n_slices))
    raise ValueError(
        f"unknown outer walk {kind!r}; expected 'exp2' or 'ring'")


def _inner_graph(slice_size: int, kind: str) -> nx.DiGraph:
    if kind == "exp2":
        return ExponentialTwoGraph(slice_size)
    if kind == "ring":
        return RingGraph(slice_size)
    raise ValueError(
        f"unknown inner topology {kind!r}; expected 'exp2' or 'ring'")


def hierarchical_two_level(n: int, n_slices: int, *,
                           inner: str = "exp2", outer: str = "exp2",
                           outer_every: int = 1,
                           outer_self_weight: float = 0.5,
                           cadence_corrected: bool = True,
                           ) -> HierarchicalTopology:
    """Build the standard two-level topology: dense ``inner`` (exp2/ring)
    inside each of ``n_slices`` equal slices, one-peer dynamic ``outer``
    (exp2 shifts / ring) between slices every ``outer_every`` steps.

    ``outer_self_weight`` is the CADENCE-1 per-exchange self weight
    ``theta`` (default 0.5 — with exp2 shifts and 0.5/0.5 weights a full
    outer sweep of ``log2(n_slices)`` exchanges is an EXACT inter-slice
    average).  With ``cadence_corrected`` (default) the stored per-outer-
    step weight is ``theta ** outer_every``: one cadence-``k`` exchange
    then carries the outer mixing mass of ``k`` cadence-1 exchanges
    (matching self-retention of the non-shared component per ``k``-step
    window), instead of silently diluting the outer level by ``1/k``.
    Any value keeps every operator doubly stochastic, so the ``k``-step
    effective operator still averages — the correction tunes the rate,
    never the preserved mean.
    """
    if n_slices < 1 or n % n_slices:
        raise ValueError(
            f"{n} ranks do not split into {n_slices} equal slices")
    if outer_every < 1:
        raise ValueError(f"outer_every must be >= 1, got {outer_every}")
    if not 0.0 < outer_self_weight < 1.0:
        raise ValueError("outer_self_weight must be in (0, 1), got "
                         f"{outer_self_weight}")
    slice_size = n // n_slices
    theta = (outer_self_weight ** outer_every if cadence_corrected
             else outer_self_weight)
    return HierarchicalTopology(
        n=n, n_slices=n_slices, slice_size=slice_size,
        inner=_inner_graph(slice_size, inner),
        outer_phases=_outer_phase_table(n_slices, outer),
        outer_every=int(outer_every),
        outer_self_weight=float(theta),
        inner_kind=inner, outer_kind=outer)
