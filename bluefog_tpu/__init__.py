"""bluefog_tpu: a TPU-native decentralized training framework.

A from-scratch JAX/XLA re-design of BlueFog's capability set (neighbor
averaging and gossip over static/dynamic virtual topologies, hierarchical
machine-level graphs, one-sided async windows, push-sum) with topologies
compiled to ``lax.ppermute`` / ``psum`` schedules over TPU mesh axes instead of
an MPI/NCCL background thread with rank-0 negotiation.

Public surface mirrors ``import bluefog.torch as bf`` (reference
``bluefog/torch/__init__.py:39-77``):

>>> import bluefog_tpu as bf
>>> bf.init()
>>> y = bf.neighbor_allreduce(x)
"""

from bluefog_tpu import _compat  # noqa: F401  — jax version shims first
from bluefog_tpu import topology  # noqa: F401
from bluefog_tpu import topology as topology_util  # parity alias  # noqa: F401

from bluefog_tpu.version import __version__  # noqa: F401

# Module-level context API (init/rank/size/ops) — imported lazily to keep
# `import bluefog_tpu` cheap and jax-initialization-free until first use.
from bluefog_tpu.basics import (  # noqa: F401
    init,
    init_distributed,
    shutdown,
    initialized,
    suspend,
    resume,
    suspended,
    size,
    rank,
    local_size,
    local_rank,
    machine_size,
    machine_rank,
    is_homogeneous,
    owned_ranks,
    mesh,
    hierarchical_mesh,
    set_topology,
    set_machine_topology,
    placement_info,
    synthesis_info,
    membership_info,
    gang_info,
    load_topology,
    load_machine_topology,
    in_neighbor_ranks,
    out_neighbor_ranks,
    in_neighbor_machine_ranks,
    out_neighbor_machine_ranks,
    allreduce,
    allreduce_,
    allreduce_nonblocking,
    allreduce_nonblocking_,
    allgather,
    allgather_nonblocking,
    allgather_v,
    broadcast,
    broadcast_,
    broadcast_nonblocking,
    broadcast_nonblocking_,
    broadcast_optimizer_state,
    set_skip_negotiate_stage,
    get_skip_negotiate_stage,
    mpi_threads_supported,
    nccl_built,
    unified_mpi_window_model_supported,
    neighbor_allgather,
    neighbor_allgather_nonblocking,
    neighbor_allgather_v,
    neighbor_allreduce,
    neighbor_allreduce_nonblocking,
    dynamic_neighbor_allreduce,
    dynamic_neighbor_allreduce_nonblocking,
    hierarchical_neighbor_allreduce,
    hierarchical_neighbor_allreduce_nonblocking,
    dynamic_hierarchical_neighbor_allreduce,
    dynamic_hierarchical_neighbor_allreduce_nonblocking,
    hierarchical_gossip,
    hierarchical_gossip_nonblocking,
    hierarchical_gossip_info,
    local_allreduce,
    local_allreduce_nonblocking,
    pair_gossip,
    pair_gossip_nonblocking,
    poll,
    wait,
    synchronize,
    barrier,
    to_numpy,
    broadcast_parameters,
    allreduce_parameters,
)

from bluefog_tpu.ops.window import (  # noqa: F401
    win_create,
    win_free,
    win_put,
    win_put_nonblocking,
    win_get,
    win_get_nonblocking,
    win_accumulate,
    win_accumulate_nonblocking,
    win_update,
    win_update_then_collect,
    win_wait,
    win_poll,
    win_mutex,
    win_fence,
    win_flush,
    win_state_dict,
    win_load_state_dict,
    get_win_version,
    get_current_created_window_names,
    win_associated_p,
    turn_on_win_ops_with_associated_p,
    turn_off_win_ops_with_associated_p,
    # Barrier-free async gossip (BLUEFOG_TPU_ASYNC): fold held-back
    # stale mass / read the async block programmatically.
    win_fold_stale_residuals,
    async_info,
)

# Zero-copy XLA window put path (BLUEFOG_TPU_WIN_XLA) diagnostics:
# armed/disarm-reason/handler capability, for operators and the bench.
from bluefog_tpu.ops.xlaffi import info as win_xla_info  # noqa: F401

from bluefog_tpu import data  # noqa: F401  (DistributedSampler, ShardedLoader)
from bluefog_tpu import optim  # noqa: F401  (Distributed*Optimizer family)

from bluefog_tpu.utils.timeline import (  # noqa: F401
    timeline_start_activity,
    timeline_end_activity,
    timeline_context,
    start_timeline,
    stop_timeline,
)

from bluefog_tpu.utils import telemetry  # noqa: F401
from bluefog_tpu.utils.telemetry import telemetry_snapshot  # noqa: F401

# Transport flight recorder (BLUEFOG_TPU_FLIGHT_RECORDER): dump the
# in-memory event ring to flightrec.<rank>.bin — the gossip black box
# `python -m bluefog_tpu.tools trace-gossip` merges across ranks.
from bluefog_tpu.utils.flightrec import dump as flight_recorder_dump  # noqa: F401,E501
# Link observatory (BLUEFOG_TPU_LINK_OBS): the cluster-wide measured
# link matrix — per-edge delay/jitter/divergence plus the hot edge —
# assembled over the aggregate-snapshot collective (call on all ranks).
from bluefog_tpu.utils.linkobs import link_report  # noqa: F401
# Elastic scale-up / coordinator-free bootstrap (BLUEFOG_TPU_ELASTIC_JOIN):
# bf.gang.init_elastic() / bf.gang.join_gang() — see docs/operations.md
# "Growing the gang".
from bluefog_tpu.ops import gang  # noqa: F401

from bluefog_tpu.utils import profiler  # noqa: F401
from bluefog_tpu.utils.profiler import step_profile  # noqa: F401
