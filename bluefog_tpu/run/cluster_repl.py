"""Multi-machine interactive sessions: a rank-0 front-end driving a worker
fleet — a line REPL (``repl_main``) or a real Jupyter KERNEL
(``kernel_main``), sharing one cell-shipping channel.

Parity: reference ``run/interactive_run.py:271-420`` (``ibfrun`` multi-machine
mode boots an ipcontroller + ssh-launched ipengines so one notebook drives the
MPI world).  The TPU-native counterpart has no ipyparallel: JAX multi-process
SPMD requires every process to run the SAME program, so the "engine fleet" is
a set of exec-loop workers and the "controller" is a rank-0 front-end that
ships each complete cell to every worker over a TCP control channel, then
executes it locally — collectives inside a cell line up across the gang
exactly as in a batch run.  ``kernel_main`` puts an ipykernel in front of the
same channel: a NOTEBOOK connected to the standard Jupyter connection file
drives the whole multi-machine gang, the reference's ipyparallel role.

Wire protocol (length-prefixed JSON): ``{"op": "exec", "src": ...}`` answered
by ``{"ok": true}`` or ``{"ok": false, "tb": ...}``; ``{"op": "exit"}`` ends
the session.  Cells run CONCURRENTLY on workers and the front-end — the ack
is collected only after the local exec, because a collective would otherwise
deadlock (workers blocked in the op, front-end blocked on acks).
"""

from __future__ import annotations

import argparse
import code
import json
import os
import socket
import struct
import sys
import time
import traceback

__all__ = ["main", "worker_main", "repl_main", "kernel_main", "Fleet",
           "ClusterConsole", "bfstat_text"]

_ACK_TIMEOUT = float(os.environ.get("BLUEFOG_TPU_IBF_ACK_TIMEOUT", "600"))

# ``%bfstat``: the one status "magic" both front-ends understand.  It is
# rewritten into this plain-Python cell and shipped like any other — every
# rank (front-end AND workers) prints its own gossip-health line, so a
# wedged worker is visible from the notebook (reference ibfrun had no
# equivalent; the closest is mpirun users ssh-ing around the fleet).
_BFSTAT_SRC = ("from bluefog_tpu.run.cluster_repl import bfstat_text as "
               "_bf_stat_fn; print(_bf_stat_fn(), flush=True)")


def bfstat_text() -> str:
    """One process's status block: identity, topology, windows, health and
    the comm-telemetry snapshot (``utils/telemetry``)."""
    import bluefog_tpu as bf
    from bluefog_tpu.utils import telemetry
    if not bf.initialized():
        return "[bfstat] bluefog_tpu not initialized"
    import jax
    lines = [
        f"[bfstat] proc {jax.process_index()}/{jax.process_count()}: "
        f"ranks {bf.owned_ranks()} of {bf.size()}"
        + (" (SUSPENDED)" if bf.suspended() else "")]
    topo = bf.load_topology()
    if topo is not None:
        lines.append(f"[bfstat] topology: {topo.number_of_nodes()} nodes, "
                     f"{topo.number_of_edges()} edges"
                     + (" (weighted)" if bf.basics.is_topo_weighted()
                        else ""))
    health = telemetry.health()
    port = telemetry.server_port()
    windows = bf.get_current_created_window_names()
    lines.append(
        f"[bfstat] health: {health['status']}"
        + ("; overdue: " + ", ".join(
            f"{o['op']} ({o['waited_sec']:.0f}s)"
            for o in health["overdue_ops"])
           if health["overdue_ops"] else "")
        + (f"; unreachable ranks: {health['unreachable_peer_ranks']}"
           if health.get("unreachable_peer_ranks") else "")
        + (f"; windows: {', '.join(windows)}" if windows else "")
        + (f"; /metrics on :{port}" if port else ""))
    member = health.get("membership")
    if member:
        import datetime
        when = member.get("last_change_unix")
        lines.append(
            f"[bfstat] membership: epoch {member['epoch']}, "
            f"{len(member['active_ranks'])}/{member['world_ranks']} ranks "
            f"active {member['active_ranks']}"
            + (f"; suspects {member['suspect_ranks']}"
               if member.get("suspect_ranks") else "")
            + (f"; admitting ranks {member['pending_join_ranks']}"
               if member.get("pending_join_ranks") else "")
            + (" (JOINING)" if member.get("joining") else "")
            + (" (EVICTED)" if member.get("evicted") else "")
            + (f"; last change {datetime.datetime.fromtimestamp(when):%H:%M:%S}"
               if when else ""))
    gd = health.get("gang_directory")
    if gd:
        # Elastic scale-up (ops/gang.py): the replicated endpoint
        # directory this process would serve a joining replacement from.
        lines.append(
            f"[bfstat] gang directory: epoch {gd['epoch']}, "
            f"{len(gd.get('active_procs', []))} procs / "
            f"{gd.get('endpoints', 0)} endpoints"
            + (f"; vacant ranks {gd['vacant_ranks']}"
               if gd.get("vacant_ranks") else "")
            + (f"; grants {gd['grants_total']}"
               if gd.get("grants_total") else "")
            + (f"; persisted @{gd['persist_prefix']}"
               if gd.get("persist_prefix") else ""))
    ages = health.get("contribution_age")
    if ages:
        # Per-edge gossip staleness (wire trace tags): how old each
        # in-neighbor's contribution was when it folded here — the line
        # an operator reads to spot a lagging edge before it wedges.
        parts = ", ".join(
            f"src {s} {a.get('freshest_sec', 0):.3f}.."
            f"{a.get('stalest_sec', 0):.3f}s"
            for s, a in sorted(ages.items(), key=lambda kv: int(kv[0])))
        lines.append(f"[bfstat] contribution age: {parts}")
    a = health.get("async")
    if a:
        # Barrier-free async mode: my step clock vs the freshest peer,
        # the staleness policy in force, and how much mass it has held
        # back — the line an operator reads to see whether a straggler
        # is being absorbed (stale counters ticking) or the fleet is
        # actually coupled (lag pinned near 0 by the backstop).
        rej = sum(a.get("stale_rejected", {}).values())
        dwn = sum(a.get("stale_downweighted", {}).values())
        lines.append(
            f"[bfstat] async: step {a['step']}, lag {a['step_lag']}, "
            f"bound {a['staleness_steps']} steps ({a['policy']}), "
            f"collect every {a['collect_every']}"
            + (f"; stale rejected {rej:g}" if rej else "")
            + (f", downweighted {dwn:g}" if dwn else ""))
    links = health.get("links")
    if links:
        # Link observatory (utils/linkobs.py): the worst measured edge,
        # how far reality has diverged from the placement model, and the
        # SLO engine's verdict — the line an operator reads to tell "a
        # link is slow" from "a rank is slow".
        slo = links.get("slo", {})
        lines.append(
            f"[bfstat] links: {links.get('edges', 0)} edge(s)"
            + (f", worst {links['worst_edge']} "
               f"({links['worst_delay_us']:.0f} us)"
               if links.get("worst_edge") else "")
            + (f", max divergence x{links['max_divergence_ratio']:.2f}"
               if links.get("max_divergence_ratio") is not None else "")
            + (f"; SLO BREACHED: {', '.join(slo['breached'])}"
               if slo.get("breached") else
               (f"; SLO ok ({len(slo['rules'])} rule(s))"
                if slo.get("rules") else "")))
    straggler = health.get("straggler")
    if straggler:
        slow = straggler["slowest_rank"]
        lines.append(
            f"[bfstat] straggler: score {straggler['straggler_score']:.2f}"
            f" (x{straggler.get('slowest_over_mean', 1.0):.2f} mean), "
            f"slowest rank {slow} "
            f"({straggler['step_seconds'][slow]:.4f}s vs mean "
            f"{straggler['mean_sec']:.4f}s over "
            f"{len(straggler['step_seconds'])} ranks)")
    snap = telemetry.snapshot()
    if snap:
        for k in sorted(snap):
            lines.append(f"[bfstat]   {k} = {snap[k]:g}")
    else:
        lines.append("[bfstat]   (telemetry registry empty"
                     + ("" if telemetry.enabled()
                        else " — BLUEFOG_TPU_TELEMETRY=0") + ")")
    return "\n".join(lines)


def _gang_token() -> str:
    """Shared secret binding workers to THIS gang.

    Workers exec() whatever arrives on the control channel, so both ends
    must prove they were launched by the same ``ibfrun`` invocation — the
    reference's ipyparallel mode gets this from keyed connection files
    (``run/interactive_run.py:271-420``).  The launcher exports one random
    token per gang (``BFTPU_IBF_TOKEN``); the wire carries only HMACs over
    per-connection nonces (see ``_mac`` and the handshake in
    ``worker_main``/``repl_main``), never the token itself — a rogue
    listener on the ctrl port cannot harvest it from a connecting worker."""
    return os.environ.get("BFTPU_IBF_TOKEN", "")


def _mac(token: str, nonce: str) -> str:
    import hashlib
    import hmac
    return hmac.new(token.encode(), nonce.encode(),
                    hashlib.sha256).hexdigest()


def _mac_ok(token: str, nonce: str, mac) -> bool:
    import hmac
    return isinstance(mac, str) and hmac.compare_digest(
        _mac(token, nonce), mac)


def _warn_if_unauthenticated(token: str, side: str) -> None:
    if not token:
        print(f"[ibfrun] {side}: BFTPU_IBF_TOKEN is not set — the control "
              "channel is UNAUTHENTICATED (fine for manual single-machine "
              "use; ibfrun's launcher always sets a per-gang token)",
              file=sys.stderr)


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> dict:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise EOFError("control channel closed")
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise EOFError("control channel closed")
        data += chunk
    return json.loads(data.decode())


def _boot_bf():
    """Shared SPMD boot: honor the virtual-mesh env the launcher prepared
    (site hooks can pin jax_platforms, so env vars alone are not enough),
    then rendezvous."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import bluefog_tpu as bf
    bf.init_distributed()
    return bf


def worker_main(ctrl: str) -> int:
    """Exec-loop worker (the reference's ipengine role): rendezvous, connect
    to the REPL's control socket, complete the mutual HMAC handshake, run
    every shipped cell in a persistent namespace.

    Handshake (nothing secret on the wire): the REPL sends a nonce
    challenge; the worker answers with ``HMAC(token, repl_nonce)`` plus its
    own nonce; the REPL's welcome carries ``HMAC(token, worker_nonce)``.
    Each side proves possession of the gang token to the other, so neither
    a rogue ctrl listener (which could otherwise harvest a plaintext
    credential and replay it) nor a rogue client can enter the exec loop
    — including its ``exit`` op."""
    bf = _boot_bf()
    host, port_s = ctrl.rsplit(":", 1)
    deadline = time.monotonic() + 120
    sock = None
    while sock is None:
        try:
            sock = socket.create_connection((host, int(port_s)), timeout=10)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.2)
    token = _gang_token()
    _warn_if_unauthenticated(token, f"worker rank {int(bf.rank())}")
    import secrets
    sock.settimeout(30)
    challenge = _recv_msg(sock)
    if challenge.get("op") != "challenge" or "nonce" not in challenge:
        raise ConnectionError(
            "ibfrun worker: the ctrl endpoint did not issue a handshake "
            "challenge — refusing to join (is something else listening "
            "on the control port?)")
    my_nonce = secrets.token_hex(16)
    _send_msg(sock, {"op": "hello", "rank": int(bf.rank()),
                     "nonce": my_nonce,
                     "mac": _mac(token, str(challenge["nonce"]))})
    welcome = _recv_msg(sock)
    if (welcome.get("op") != "welcome"
            or not _mac_ok(token, my_nonce, welcome.get("mac"))):
        raise ConnectionError(
            "ibfrun worker: the ctrl endpoint failed the gang-token "
            "handshake — refusing to run cells from it")
    sock.settimeout(None)
    ns: dict = {"bf": bf, "__name__": "__main__"}
    while True:
        try:
            msg = _recv_msg(sock)
        except EOFError:
            break  # REPL gone: shut down with it
        if msg.get("op") == "exit":
            break
        seq = msg.get("seq")
        try:
            exec(compile(msg["src"], "<cluster>", "exec"), ns)  # noqa: S102
        except SystemExit:
            _send_msg(sock, {"ok": True, "seq": seq})
            break
        except BaseException:  # noqa: BLE001 — report, stay alive
            _send_msg(sock, {"ok": False, "tb": traceback.format_exc(),
                             "seq": seq})
            continue
        _send_msg(sock, {"ok": True, "seq": seq})
    try:
        sock.close()
    except OSError:
        pass
    bf.shutdown()
    return 0


class Fleet:
    """The cell-shipping channel to the worker exec loops — shared by the
    line REPL (:class:`ClusterConsole`) and the Jupyter kernel
    (:func:`kernel_main`)."""

    def __init__(self, workers):
        self._workers = list(workers)  # live [(rank, sock)]
        self._seq = 0

    def _drop(self, rank, sock, why):
        print(f"[ibfrun] rank {rank}: control channel lost ({why}); "
              "continuing without it", file=sys.stderr)
        try:
            sock.close()
        except OSError:
            pass
        self._workers = [(r, s) for r, s in self._workers if s is not sock]

    def ship(self, source: str) -> int:
        """Send one cell to every worker (returns its sequence number).
        The connections were mutually authenticated at handshake time, so
        messages need no per-cell credential."""
        self._seq += 1
        for rank, sock in list(self._workers):
            try:
                _send_msg(sock, {"op": "exec", "src": source,
                                 "seq": self._seq})
            except OSError as e:
                self._drop(rank, sock, e)
        return self._seq

    def collect_acks(self) -> None:
        """One ack per worker for the LAST shipped cell.  Sequence numbers
        keep the pairing exact: a late ack from a previous slow cell is
        drained and discarded, never attributed to the current one; a
        worker that exceeds the timeout stays in the fleet (its stale ack
        is skipped on the next collect), while a closed channel removes
        it."""
        for rank, sock in list(self._workers):
            # Scope the timeout to THIS recv loop: leaking it onto the
            # socket would make later _send_msg sendall calls raise
            # socket.timeout on a slow-but-healthy worker (long cell,
            # full TCP buffer) and permanently drop it from the fleet —
            # after which the SPMD gang deadlocks on the next collective.
            sock.settimeout(_ACK_TIMEOUT)
            try:
                while True:
                    try:
                        reply = _recv_msg(sock)
                    except socket.timeout:
                        print(f"[ibfrun] rank {rank}: no ack within "
                              f"{_ACK_TIMEOUT:.0f}s (cell still running "
                              "there?)", file=sys.stderr)
                        break
                    except (EOFError, OSError) as e:
                        self._drop(rank, sock, e)
                        break
                    if reply.get("seq") == self._seq:
                        if not reply.get("ok"):
                            tb = reply.get("tb", "").rstrip().splitlines()
                            tail = tb[-1] if tb else "unknown error"
                            print(f"[ibfrun] rank {rank} raised: {tail}",
                                  file=sys.stderr)
                        break
                    # Stale ack from an earlier timed-out cell: drain it.
            finally:
                try:
                    sock.settimeout(None)
                except OSError:
                    pass  # already closed by _drop

    def close(self) -> None:
        for _, sock in self._workers:
            try:
                _send_msg(sock, {"op": "exit"})
                sock.close()
            except OSError:
                pass
        self._workers = []


class ClusterConsole(code.InteractiveConsole):
    """REPL that ships each COMPLETE cell to the worker fleet before running
    it locally (concurrent SPMD execution), then surfaces worker errors."""

    def __init__(self, workers, locals=None):  # noqa: A002 — stdlib name
        super().__init__(locals=locals)
        self._fleet = workers if isinstance(workers, Fleet) \
            else Fleet(workers)

    @property
    def _workers(self):  # introspection/tests
        return self._fleet._workers

    def runsource(self, source, filename="<input>", symbol="single"):
        if source.strip() == "%bfstat":
            # Status "magic": rewritten to a plain-Python cell so it runs
            # SPMD like everything else — every rank prints its own block.
            source = _BFSTAT_SRC
        try:
            compiled = self.compile(source, filename, symbol)
        except (OverflowError, SyntaxError, ValueError):
            self.showsyntaxerror(filename)
            return False
        if compiled is None:
            return True  # incomplete cell: keep buffering
        self._fleet.ship(source)
        self.runcode(compiled)
        self._fleet.collect_acks()
        return False


def _accept_fleet(ctrl: str, expect: int, side: str):
    """Rank-0 side shared by the REPL and the kernel: boot the SPMD world,
    listen on the ctrl endpoint, mutually authenticate ``expect`` workers
    (HMAC challenge-response, see :func:`worker_main`).  Returns
    ``(srv, workers, bf)`` with ``workers`` rank-sorted."""
    host, port_s = ctrl.rsplit(":", 1)
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        # Bind the coordinator interface the workers were told to dial,
        # not every interface on the machine.
        srv.bind((host, int(port_s)))
    except OSError as e:
        import errno
        if e.errno != errno.EADDRNOTAVAIL:
            raise  # EADDRINUSE etc: surface the REAL cause, don't mask it
        # The --ctrl host does not resolve to a local interface (NAT'd or
        # misresolved name): fall back to a wildcard bind, LOUDLY — the
        # exec() channel is now reachable on every interface.
        print(f"[ibfrun] ctrl host {host!r} is not a local address; "
              "binding ALL interfaces (the handshake still gates exec)",
              file=sys.stderr)
        srv.bind(("", int(port_s)))
    srv.listen(expect)
    bf = _boot_bf()
    token = _gang_token()
    _warn_if_unauthenticated(token, side)
    import secrets
    workers = []
    # 120s of patience PER MISSING WORKER (as before this had a handshake),
    # not a shared deadline a slow ssh fan-out could overrun.
    srv.settimeout(120)
    while len(workers) < expect:
        conn, peer = srv.accept()
        try:
            conn.settimeout(10)  # a silent connection must not wedge accept
            nonce = secrets.token_hex(16)
            _send_msg(conn, {"op": "challenge", "nonce": nonce})
            hello = _recv_msg(conn)
        except (EOFError, OSError, ValueError):
            hello = {}
        if (hello.get("op") != "hello"
                or not _mac_ok(token, nonce, hello.get("mac"))):
            # A connection that cannot prove possession of this gang's
            # secret is not a worker: close it and keep listening (it must
            # not consume one of the ``expect`` fleet slots).
            print(f"[ibfrun] rejected unauthenticated connection from "
                  f"{peer}", file=sys.stderr)
            try:
                conn.close()
            except OSError:
                pass
            continue
        # Prove OUR possession back (the worker refuses a rogue listener).
        _send_msg(conn, {"op": "welcome",
                         "mac": _mac(token, str(hello.get("nonce", "")))})
        conn.settimeout(None)
        workers.append((int(hello.get("rank", -1)), conn))
    workers.sort()
    return srv, workers, bf


def repl_main(ctrl: str, expect: int) -> int:
    """Rank-0 side: listen for ``expect`` workers, rendezvous, drive the
    interactive session."""
    srv, workers, bf = _accept_fleet(ctrl, expect, "repl")
    print(f"bluefog_tpu interactive: {bf.size()} rank(s) across "
          f"{bf.machine_size()} process(es) ready; every cell runs SPMD on "
          "the whole gang", flush=True)
    fleet = Fleet(workers)
    console = ClusterConsole(fleet, locals={"bf": bf,
                                            "__name__": "__main__"})
    try:
        console.interact(banner="", exitmsg="")
    except SystemExit:
        pass
    fleet.close()
    srv.close()
    bf.shutdown()
    return 0


def kernel_main(ctrl: str, expect: int, conn_file: str) -> int:
    """Rank-0 side as a JUPYTER KERNEL: a notebook client connected to
    ``conn_file`` (standard Jupyter connection file, written on startup)
    drives the whole multi-machine gang — every executed cell is shipped
    to the worker fleet before running in the kernel, so collectives line
    up SPMD exactly as in the line REPL.  This is the reference's
    multi-machine-notebook role (ipcontroller + ssh'd ipengines,
    ``run/interactive_run.py:271-420``) on the one authenticated
    cell-shipping channel; Jupyter's own connection-file HMAC key
    authenticates the notebook client side."""
    srv, workers, bf = _accept_fleet(ctrl, expect, "kernel")
    fleet = Fleet(workers)

    from ipykernel.ipkernel import IPythonKernel
    from ipykernel.kernelapp import IPKernelApp

    class ClusterKernel(IPythonKernel):
        implementation = "bluefog_tpu-cluster"
        banner = ("bluefog_tpu SPMD cluster kernel: every cell runs on "
                  "the whole gang")

        async def do_execute(self, code, silent, store_history=True,
                             user_expressions=None, allow_stdin=False,
                             **kwargs):
            if code.strip() == "%bfstat":
                # The one supported "magic": rewritten to plain Python and
                # shipped SPMD, so every rank reports its gossip health.
                code = _BFSTAT_SRC
            # Normalize line endings BEFORE the guard comparison: CRLF
            # cells from some Jupyter clients are plain Python that the
            # transformer normalizes textually — without this they would
            # be spuriously rejected as IPython-only syntax.  The
            # normalized form is also what ships (workers' exec and the
            # local run must see the same bytes).
            code = code.replace("\r\n", "\n").replace("\r", "\n")
            # IPython-only syntax (magics, !shell, obj?) would execute in
            # THIS kernel but be a SyntaxError in the workers' plain
            # exec() — the kernel could then enter a collective the
            # workers never reach and hang the gang.  Reject such cells
            # BEFORE shipping or executing anything, keeping both sides
            # in lockstep.
            transformed = self.shell.transform_cell(code)
            if transformed.strip() != code.strip():
                return await super().do_execute(
                    "raise RuntimeError('ibfrun cluster kernel: "
                    "IPython-only syntax (magics/!shell/?help) cannot run "
                    "SPMD on the worker fleet — use plain Python in "
                    "cluster cells')",
                    silent, store_history=False,
                    user_expressions=user_expressions,
                    allow_stdin=allow_stdin, **kwargs)
            fleet.ship(code)
            try:
                # Local exec runs CONCURRENTLY with the workers' —
                # collectives inside the cell rendezvous across the gang.
                return await super().do_execute(
                    code, silent, store_history=store_history,
                    user_expressions=user_expressions,
                    allow_stdin=allow_stdin, **kwargs)
            finally:
                # Inside do_execute sys.stderr forwards to the client, so
                # worker errors/timeouts surface in the notebook.
                fleet.collect_acks()

    app = IPKernelApp.instance(connection_file=conn_file,
                               kernel_class=ClusterKernel)
    app.initialize([])
    app.kernel.shell.user_ns.update({"bf": bf})
    print(f"bluefog_tpu cluster kernel: {bf.size()} rank(s) across "
          f"{bf.machine_size()} process(es); connection file "
          f"{app.abs_connection_file}", flush=True)
    try:
        app.start()  # returns after the client's shutdown_request
    except SystemExit:
        pass
    fleet.close()
    srv.close()
    bf.shutdown()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bf-cluster-repl", description=__doc__)
    p.add_argument("--ctrl", required=True, help="rank-0 control host:port")
    p.add_argument("--repl", action="store_true",
                   help="run the rank-0 REPL (default: worker exec loop)")
    p.add_argument("--kernel-file", default=None,
                   help="run the rank-0 side as a Jupyter kernel writing "
                        "this connection file (notebook front-end)")
    p.add_argument("--expect", type=int, default=None,
                   help="worker connections the rank-0 side waits for "
                        "(default: processes - 1)")
    args = p.parse_args(argv)
    if args.repl or args.kernel_file:
        expect = args.expect
        if expect is None:
            expect = int(os.environ.get("BFTPU_NUM_PROCESSES", "1")) - 1
        if args.kernel_file:
            return kernel_main(args.ctrl, expect, args.kernel_file)
        return repl_main(args.ctrl, expect)
    return worker_main(args.ctrl)


if __name__ == "__main__":
    sys.exit(main())
