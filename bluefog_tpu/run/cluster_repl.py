"""Multi-machine interactive sessions: a rank-0 REPL driving a worker fleet.

Parity: reference ``run/interactive_run.py:271-420`` (``ibfrun`` multi-machine
mode boots an ipcontroller + ssh-launched ipengines so one notebook drives the
MPI world).  The TPU-native counterpart has no ipyparallel: JAX multi-process
SPMD requires every process to run the SAME program, so the "engine fleet" is
a set of exec-loop workers and the "controller" is a rank-0 REPL that ships
each complete cell to every worker over a TCP control channel, then executes
it locally — collectives inside a cell line up across the gang exactly as in
a batch run.

Wire protocol (length-prefixed JSON): ``{"op": "exec", "src": ...}`` answered
by ``{"ok": true}`` or ``{"ok": false, "tb": ...}``; ``{"op": "exit"}`` ends
the session.  Cells run CONCURRENTLY on workers and the REPL — the ack is
collected only after the local exec, because a collective would otherwise
deadlock (workers blocked in the op, REPL blocked on acks).
"""

from __future__ import annotations

import argparse
import code
import json
import os
import socket
import struct
import sys
import time
import traceback

__all__ = ["main", "worker_main", "repl_main", "ClusterConsole"]

_ACK_TIMEOUT = float(os.environ.get("BLUEFOG_TPU_IBF_ACK_TIMEOUT", "600"))


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> dict:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise EOFError("control channel closed")
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise EOFError("control channel closed")
        data += chunk
    return json.loads(data.decode())


def _boot_bf():
    """Shared SPMD boot: honor the virtual-mesh env the launcher prepared
    (site hooks can pin jax_platforms, so env vars alone are not enough),
    then rendezvous."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import bluefog_tpu as bf
    bf.init_distributed()
    return bf


def worker_main(ctrl: str) -> int:
    """Exec-loop worker (the reference's ipengine role): rendezvous, connect
    to the REPL's control socket, run every shipped cell in a persistent
    namespace."""
    bf = _boot_bf()
    host, port_s = ctrl.rsplit(":", 1)
    deadline = time.monotonic() + 120
    sock = None
    while sock is None:
        try:
            sock = socket.create_connection((host, int(port_s)), timeout=10)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.2)
    _send_msg(sock, {"op": "hello", "rank": int(bf.rank())})
    ns: dict = {"bf": bf, "__name__": "__main__"}
    while True:
        try:
            msg = _recv_msg(sock)
        except EOFError:
            break  # REPL gone: shut down with it
        if msg.get("op") == "exit":
            break
        seq = msg.get("seq")
        try:
            exec(compile(msg["src"], "<cluster>", "exec"), ns)  # noqa: S102
        except SystemExit:
            _send_msg(sock, {"ok": True, "seq": seq})
            break
        except BaseException:  # noqa: BLE001 — report, stay alive
            _send_msg(sock, {"ok": False, "tb": traceback.format_exc(),
                             "seq": seq})
            continue
        _send_msg(sock, {"ok": True, "seq": seq})
    try:
        sock.close()
    except OSError:
        pass
    bf.shutdown()
    return 0


class ClusterConsole(code.InteractiveConsole):
    """REPL that ships each COMPLETE cell to the worker fleet before running
    it locally (concurrent SPMD execution), then surfaces worker errors."""

    def __init__(self, workers, locals=None):  # noqa: A002 — stdlib name
        super().__init__(locals=locals)
        self._workers = list(workers)  # live [(rank, sock)]
        self._seq = 0

    def _drop(self, rank, sock, why):
        print(f"[ibfrun] rank {rank}: control channel lost ({why}); "
              "continuing without it", file=sys.stderr)
        try:
            sock.close()
        except OSError:
            pass
        self._workers = [(r, s) for r, s in self._workers if s is not sock]

    def runsource(self, source, filename="<input>", symbol="single"):
        try:
            compiled = self.compile(source, filename, symbol)
        except (OverflowError, SyntaxError, ValueError):
            self.showsyntaxerror(filename)
            return False
        if compiled is None:
            return True  # incomplete cell: keep buffering
        self._seq += 1
        for rank, sock in list(self._workers):
            try:
                _send_msg(sock, {"op": "exec", "src": source,
                                 "seq": self._seq})
            except OSError as e:
                self._drop(rank, sock, e)
        self.runcode(compiled)
        self._collect_acks()
        return False

    def _collect_acks(self):
        """One ack per worker for THIS cell.  Sequence numbers keep the
        pairing exact: a late ack from a previous slow cell is drained and
        discarded, never attributed to the current one; a worker that
        exceeds the timeout stays in the fleet (its stale ack is skipped on
        the next collect), while a closed channel removes it."""
        for rank, sock in list(self._workers):
            sock.settimeout(_ACK_TIMEOUT)
            while True:
                try:
                    reply = _recv_msg(sock)
                except socket.timeout:
                    print(f"[ibfrun] rank {rank}: no ack within "
                          f"{_ACK_TIMEOUT:.0f}s (cell still running "
                          "there?)", file=sys.stderr)
                    break
                except (EOFError, OSError) as e:
                    self._drop(rank, sock, e)
                    break
                if reply.get("seq") == self._seq:
                    if not reply.get("ok"):
                        tb = reply.get("tb", "").rstrip().splitlines()
                        tail = tb[-1] if tb else "unknown error"
                        print(f"[ibfrun] rank {rank} raised: {tail}",
                              file=sys.stderr)
                    break
                # Stale ack from an earlier timed-out cell: drain it.


def repl_main(ctrl: str, expect: int) -> int:
    """Rank-0 side: listen for ``expect`` workers, rendezvous, drive the
    interactive session."""
    host, port_s = ctrl.rsplit(":", 1)
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("", int(port_s)))
    srv.listen(expect)
    bf = _boot_bf()
    workers = []
    srv.settimeout(120)
    for _ in range(expect):
        conn, _ = srv.accept()
        hello = _recv_msg(conn)
        workers.append((int(hello.get("rank", -1)), conn))
    workers.sort()
    print(f"bluefog_tpu interactive: {bf.size()} rank(s) across "
          f"{bf.machine_size()} process(es) ready; every cell runs SPMD on "
          "the whole gang", flush=True)
    console = ClusterConsole(workers, locals={"bf": bf,
                                              "__name__": "__main__"})
    try:
        console.interact(banner="", exitmsg="")
    except SystemExit:
        pass
    for _, sock in workers:
        try:
            _send_msg(sock, {"op": "exit"})
            sock.close()
        except OSError:
            pass
    srv.close()
    bf.shutdown()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bf-cluster-repl", description=__doc__)
    p.add_argument("--ctrl", required=True, help="rank-0 control host:port")
    p.add_argument("--repl", action="store_true",
                   help="run the rank-0 REPL (default: worker exec loop)")
    p.add_argument("--expect", type=int, default=None,
                   help="worker connections the REPL waits for "
                        "(default: processes - 1)")
    args = p.parse_args(argv)
    if args.repl:
        expect = args.expect
        if expect is None:
            expect = int(os.environ.get("BFTPU_NUM_PROCESSES", "1")) - 1
        return repl_main(args.ctrl, expect)
    return worker_main(args.ctrl)


if __name__ == "__main__":
    sys.exit(main())
