"""Churn supervisor: elastic gossip as a service.

The control loop that fuses the pieces PRs 1-6 built separately — failure
detection (transport reachability probes, heartbeat staleness, straggler
step-lag), gossip-consistent membership consensus (``ops/membership.py``),
survivor re-planning (``bf.set_topology`` over a doubly-stochastic survivor
topology, which re-enters the PR 5/6 placement + schedule-synthesis
pipeline automatically), and restart-free recovery (window state is carried
across the re-plan by each process's OWNED rows — the same authority
contract ``utils/elastic.py`` uses for its checkpoint stitching, applied
live instead of through disk).

Usage (the training loop drives it at step boundaries)::

    sup = ChurnSupervisor()            # requires BLUEFOG_TPU_CHURN=1 and
    ...                                # a live multi-process transport
    for step in range(num_steps):
        change = sup.step(step)        # heartbeats ride a background thread
        if change is not None and change.evicted:
            break                      # this rank was voted out: exit
        train_step(...)                # windows/topology already re-planned
    sup.stop()

``step()`` returns ``None`` while the membership is stable.  When the gang
commits a new membership, the supervisor — before returning — retires the
dead peers' transport queues, frees and recreates every window under the
survivor topology (owned rows preserved, push-sum mass preserved, staging
from the dead peer dropped), and hands back the committed view so the loop
can adjust anything of its own (telemetry already records
``bf_churn_recovery_seconds``).  No rank ever restarts; no global barrier
is involved beyond the consensus itself.

Everything is inert unless ``BLUEFOG_TPU_CHURN=1``; constructing a
supervisor without it raises, and with ``=0`` no module state changes
anywhere — the legacy path is bit-identical.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from bluefog_tpu.utils import config

__all__ = ["ChurnSupervisor", "maybe_supervisor"]


class ChurnSupervisor:
    """One per-process churn control loop over the live window transport."""

    def __init__(self, *, topology_builder=None,
                 on_change: Optional[Callable] = None,
                 heartbeat_sec: Optional[float] = None,
                 probe_timeout: float = 0.75):
        cfg = config.get()
        if not cfg.churn:
            raise RuntimeError(
                "ChurnSupervisor requires BLUEFOG_TPU_CHURN=1 (default off: "
                "the churn controller must be an explicit operational "
                "decision, never ambient)")
        from bluefog_tpu import basics
        from bluefog_tpu.ops import gang, membership
        from bluefog_tpu.ops import window as W
        from bluefog_tpu.ops.transport import OP_MEMBER
        d = W._store.distrib
        if d is None:
            raise RuntimeError(
                "ChurnSupervisor needs the multi-process DCN window "
                "transport (bf.init_distributed(), or init_transport() in "
                "a chaos gang) — single-process runs have no gang to "
                "supervise")
        self._d = d
        self._W = W
        self._OP_MEMBER = OP_MEMBER
        self._n = basics.size()
        self._basics = basics
        self._membership = membership
        self._topology_builder = topology_builder
        self._on_change = on_change
        self._probe_timeout = probe_timeout
        self._hb_sec = (max(0.01, cfg.churn_heartbeat_ms / 1e3)
                        if heartbeat_sec is None else heartbeat_sec)
        # Elastic scale-up (BLUEFOG_TPU_ELASTIC_JOIN, ops/gang.py): adopt
        # the gang service a coordinator-free bootstrap or a join already
        # installed, or — when the gang came up through the classic
        # coordinator exchange with joins enabled — build the replicated
        # directory from the live transport maps, so this member can
        # grant joins and serve bootstrap replicas too.
        self._gang = gang.current() if cfg.elastic_join else None
        if cfg.elastic_join and self._gang is None:
            directory = gang.GangDirectory(
                self._n,
                {p: f"{a[0]}:{a[1]}" for p, a in d.proc_addr.items()},
                epoch=0, active=sorted(d.proc_addr),
                rank_owner=dict(d.rank_owner))
            self._gang = gang.GangService(directory)
            gang.install(self._gang)
            self._gang.persist()
        grant = self._gang.pending_grant if self._gang is not None else None
        seed = {}
        if grant is not None:
            # This process IS a granted joiner: seed the controller with
            # the committed view from the grant and propose our own
            # admission until the gang commits the grow epoch.
            seed = dict(active=grant.active, epoch=grant.epoch,
                        joining=True, my_join_ranks=grant.ranks,
                        my_endpoint=grant.my_endpoint)
        self.ctrl = membership.MembershipController(
            n_procs=len(d.proc_addr), my_proc=d.my_proc,
            rank_owner=dict(d.rank_owner),
            send_fn=self._send, probe_fn=self._probe, **seed)
        membership.install(self.ctrl)
        from bluefog_tpu.utils import chaos, telemetry
        self.chaos = chaos.ChaosInjector(
            my_ranks=[r for r, p in d.rank_owner.items() if p == d.my_proc],
            transport=d.transport,
            peer_addrs=[a for p, a in d.proc_addr.items() if p != d.my_proc])
        telemetry.set_gauge("bf_active_ranks",
                            len(self.ctrl.active_ranks()))
        telemetry.set_gauge("bf_membership_epoch", self.ctrl.epoch)
        self._stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True, name="bf-churn-hb")
        self._hb_thread.start()

    # -- plumbing ----------------------------------------------------------

    def _addr_of(self, proc: int):
        """A peer's transport endpoint: the rank directory, with the
        membership layer's join-claim hints as fallback — a pending or
        freshly admitted joiner is reachable before the grow recovery has
        extended ``proc_addr``."""
        addr = self._d.proc_addr.get(proc)
        if addr is None:
            addr = self.ctrl.peer_endpoint_hint(proc)
        if addr is None:
            raise ConnectionError(f"no known endpoint for proc {proc}")
        return addr

    def _send(self, proc: int, payload: bytes) -> None:
        host, port = self._addr_of(proc)
        # Striped transport: membership traffic fans out across EVERY
        # stripe, preserving the PR-7 invariant that a peer whose data
        # path is wedged cannot look healthy through a side channel the
        # data never takes — with one socket per peer the heartbeat rode
        # THE data stream; with N, a single wedged stripe must still
        # wedge the heartbeats that ride it (membership messages are
        # state-based and idempotent, so the duplicate copies on healthy
        # stripes are harmless).  Single-stream sends exactly one copy,
        # the pre-stripe behavior.
        n = int(getattr(self._d.transport, "n_stripes", 1) or 1)
        for k in range(n):
            self._d.transport.send(host, port, self._OP_MEMBER, "",
                                   self._d.my_rank, -1, 0.0,
                                   np.frombuffer(payload, np.uint8),
                                   stripe=k)

    def _probe(self, proc: int) -> bool:
        try:
            socket.create_connection(self._addr_of(proc),
                                     timeout=self._probe_timeout).close()
            return True
        except (OSError, ConnectionError):
            return False

    def _hb_loop(self) -> None:
        ticks = 0
        while not self._stop.wait(self._hb_sec):
            try:
                self.ctrl.tick()
            except Exception:  # noqa: BLE001 — the heartbeat must survive
                from bluefog_tpu.utils.logging import get_logger
                get_logger().exception("churn supervisor heartbeat failed")
            ticks += 1
            if self._gang is not None and ticks % 8 == 0:
                # Directory anti-entropy at 1/8th the heartbeat cadence:
                # state-based and idempotent, so the only cost of a slow
                # push is how long a freshly persisted replica lags.
                try:
                    self._gang.announce()
                except Exception:  # noqa: BLE001
                    pass
            if self.ctrl.evicted:
                return

    # -- the step-boundary API --------------------------------------------

    def step(self, step: int):
        """Advance the supervisor at a training-step boundary.  Applies any
        chaos fault scheduled for this step, feeds the step counter into
        the heartbeats (straggler detection), and — when the gang has
        committed a membership change — performs the full recovery before
        returning the committed :class:`~bluefog_tpu.ops.membership.
        MembershipView` (``None`` when stable).  Recovery runs on the
        CALLER's thread: the re-plan swaps topology and windows, which
        must not race the training loop's own window ops."""
        self.ctrl.note_step(step)
        self.chaos.apply(step)
        # Step-boundary tick for the link observatory: divergence/rate
        # refresh + SLO evaluation (async loops also tick it through
        # set_async_step — harmless, breaches are latched).
        from bluefog_tpu.utils import linkobs
        linkobs.on_step(step)
        # Self-tuning control plane (utils/tuner.py): divergence check +
        # adaptation at this step boundary — same caller's-thread contract
        # as recovery, since an epoch may swap topology and windows.  A
        # no-op unless BLUEFOG_TPU_TUNE is armed.
        from bluefog_tpu.utils import tuner
        tuner.tick(step)
        view = self.ctrl.poll_change()
        if view is None:
            return None
        if view.evicted:
            # The gang voted this rank out: its black box is the only
            # record of what its transport saw leading up to eviction —
            # dump before the process exits.
            from bluefog_tpu.utils import flightrec
            flightrec.dump(reason=f"evicted at epoch {view.epoch}")
            self._stop.set()
            return view
        self._recover(view)
        if self._on_change is not None:
            self._on_change(view)
        return view

    def _recover(self, view) -> None:
        """Survivor-only re-plan + restart-free resume, timed into
        ``bf_churn_recovery_seconds``.

        1. Retire the dead peers' transport sender queues (their in-flight
           gossip has nowhere to go; the per-peer error-epoch tokens
           already scoped any overlapped op failures to exactly them).
           ``drop_peer`` covers BOTH transport hot paths AND every
           transport stripe: with ``BLUEFOG_TPU_WIN_NATIVE`` on it
           retires all N of the peer's C++ stripe queues in one call, so
           every stripe worker exits instead of retrying into a closed
           socket (no N-1 orphan workers) and every per-stripe
           queue-depth gauge is cleared — discarded messages counted in
           ``bf_win_tx_dropped_msgs_total`` as always.
        2. Snapshot every window's OWNED rows + push-sum mass — each
           process is authoritative for its own ranks, the same ownership
           contract ``elastic.py`` stitches checkpoints by.
        3. Re-enter ``bf.set_topology`` with the survivor topology
           (doubly-stochastic by construction; the placement search and
           schedule synthesis re-run for the new edge set exactly as for
           any operator-initiated topology change).
        4. Recreate the windows under the new topology from the owned
           rows (staging from dead peers is dropped — zero-init — and
           fresh in-edges start clean) and restore the push-sum scalars,
           so a push-sum run keeps its conservation invariant across the
           membership change."""
        from bluefog_tpu.utils import flightrec, telemetry
        # Postmortem first: every survivor dumps its flight recorder at
        # the committed change, so the kill/eviction that caused it can
        # be reconstructed across ranks (trace-gossip merges the dumps)
        # even though the dead peer will never write its own.
        flightrec.dump(reason=f"membership change to epoch {view.epoch}")
        t0 = time.perf_counter()
        # GROWTH first (elastic scale-up, ops/gang.py): extend the
        # transport's rank directory with the admitted joiners — their
        # endpoints from the commit view, their rank takeover from the
        # consensus-updated ownership map — BEFORE the re-plan, so the
        # grown topology's new edges resolve to live endpoints.
        from bluefog_tpu.ops.gang import _ep_addr
        for proc in view.added_procs:
            ep = view.added_endpoints.get(proc)
            if ep and proc not in self._d.proc_addr:
                try:
                    self._d.proc_addr[proc] = _ep_addr(ep)
                except ValueError:
                    pass
        if view.added_ranks:
            for r in view.added_ranks:
                owner = self.ctrl.rank_owner.get(r)
                if owner is not None:
                    self._d.rank_owner[r] = owner
        dead_ranks = [r for r, p in self._d.rank_owner.items()
                      if p in set(view.removed_procs)]
        for proc in view.removed_procs:
            addr = self._d.proc_addr.get(proc)
            if addr is not None:
                self._d.transport.drop_peer(*addr)
        # Gauge hygiene (the orphan-series class drop_peer already clears
        # for bf_win_tx_queue_depth): a dead peer's per-edge contribution
        # -age gauges must not linger as live staleness claims — nor may
        # its async step/age estimates keep inflating bf_async_step_lag
        # or its per-src stale-rejection counters survive it.
        self._W.clear_contribution_age(dead_ranks)
        self._W.clear_async_staleness(dead_ranks)
        from bluefog_tpu.utils import linkobs
        linkobs.clear_edges(dead_ranks)
        W = self._W
        snaps: Dict[str, dict] = {}
        for name in W.get_current_created_window_names():
            win = W._store.get(name)
            with win.update_lock, win.lock:
                snaps[name] = {
                    "rows": np.stack([win.main[r] for r in win.owned])
                    if win.owned else
                    np.zeros((0,) + win.shape, win.dtype),
                    "p_main": dict(win.p_main),
                }
        W.win_free()
        topo = self._membership.survivor_topology(
            self._n, view.active_ranks, builder=self._topology_builder)
        self._basics.set_topology(topo, is_weighted=True)
        for name, snap in snaps.items():
            W.win_create(snap["rows"], name, zero_init=True)
            win = W._store.get(name)
            with win.lock:
                for r, p in snap["p_main"].items():
                    if r in win.p_main:
                        win.p_main[r] = p
        if self._gang is not None:
            # Fold the commit into the replicated endpoint directory and
            # persist the new replica (what a future joiner bootstraps
            # from), then push it — freshly admitted members included.
            self._gang.on_commit(view, self._d.rank_owner)
            self._gang.announce()
        dt = time.perf_counter() - t0
        telemetry.observe("bf_churn_recovery_seconds", dt)
        from bluefog_tpu.utils.logging import get_logger
        get_logger().warning(
            "churn: recovered in %.3fs — epoch %d, %d/%d ranks active"
            "%s, %d window(s) re-planned", dt, view.epoch,
            len(view.active_ranks), self._n,
            f" (admitted ranks {list(view.added_ranks)})"
            if view.added_ranks else "", len(snaps))

    # -- lifecycle / introspection ----------------------------------------

    def info(self) -> dict:
        return self.ctrl.summary()

    def stop(self) -> None:
        self._stop.set()
        self._hb_thread.join(timeout=5)
        if self._membership.current() is self.ctrl:
            self._membership.install(None)


_singleton: Optional[ChurnSupervisor] = None
_singleton_lock = threading.Lock()


def maybe_supervisor() -> Optional[ChurnSupervisor]:
    """The process-wide supervisor iff churn is enabled AND a multi-process
    transport is live; None otherwise (never raises).  Lazily constructed
    once — training loops and optimizers can call this every step."""
    global _singleton
    if not config.get().churn:
        return None
    from bluefog_tpu.ops import window as W
    if W._store.distrib is None:
        return None
    with _singleton_lock:
        if _singleton is None or _singleton._d is not W._store.distrib:
            if _singleton is not None:
                _singleton.stop()
            _singleton = ChurnSupervisor()
        return _singleton
