"""``bfrun``: process launcher for multi-host runs.

Parity: reference ``bluefog/run/run.py`` (``bfrun -np N -H h1:4,h2:4 python
train.py`` composing an ``mpirun`` command).  The TPU-native launcher has no
MPI: processes rendezvous through JAX's distributed coordinator
(``jax.distributed.initialize``), which rides gRPC over DCN — the same service
TPU pods use natively.

Modes
-----
* Local fan-out (testing / CPU):
    python -m bluefog_tpu.run -np 4 python train.py
  spawns 4 processes on this machine wired to a local coordinator; each sets
  ``BFTPU_*`` env consumed by ``bf.init_distributed()``.
* Multi-host (one process per host, reference ``-H`` flag):
    python -m bluefog_tpu.run -np 2 -H tpu-host-0,tpu-host-1 python train.py
  launches via ssh with the coordinator on the first host.
* TPU pod slices: run the same command on every host (GKE/xmanager style);
  ``bf.init_distributed()`` with no env auto-detects the TPU pod coordinator.
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys

__all__ = ["main", "build_parser"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bfrun", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-np", "--num-proc", type=int, required=True,
                   help="number of processes to launch")
    p.add_argument("-H", "--hosts", default=None,
                   help="comma-separated hosts (default: all local)")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--coordinator-port", type=int, default=None)
    p.add_argument("--devices-per-proc", type=int, default=None,
                   help="virtual CPU devices per process (testing)")
    p.add_argument("--timeline", default=None,
                   help="timeline file prefix (sets BLUEFOG_TIMELINE)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program to launch")
    return p


def _child_env(args, coord: str, rank: int) -> dict:
    env = dict(os.environ)
    env["BFTPU_COORDINATOR"] = coord
    env["BFTPU_NUM_PROCESSES"] = str(args.num_proc)
    env["BFTPU_PROCESS_ID"] = str(rank)
    if args.devices_per_proc:
        env["BFTPU_LOCAL_DEVICES"] = str(args.devices_per_proc)
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count="
                            f"{args.devices_per_proc}")
        env["JAX_PLATFORMS"] = "cpu"
    if args.timeline:
        env["BLUEFOG_TIMELINE"] = args.timeline
    return env


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("bfrun: no command given", file=sys.stderr)
        return 2

    port = args.coordinator_port or _free_port()
    hosts = (args.hosts.split(",") if args.hosts
             else ["127.0.0.1"] * args.num_proc)
    if len(hosts) != args.num_proc:
        print(f"bfrun: {args.num_proc} processes but {len(hosts)} hosts",
              file=sys.stderr)
        return 2
    coord = f"{hosts[0]}:{port}"

    procs = []
    try:
        for rank, host in enumerate(hosts):
            env = _child_env(args, coord, rank)
            if host in ("127.0.0.1", "localhost", socket.gethostname()):
                procs.append(subprocess.Popen(cmd, env=env))
            else:
                exports = " ".join(
                    f"{k}={shlex.quote(v)}" for k, v in env.items()
                    if k.startswith(("BFTPU_", "XLA_", "JAX_", "BLUEFOG")))
                remote = f"cd {shlex.quote(os.getcwd())} && {exports} " \
                         + " ".join(shlex.quote(c) for c in cmd)
                procs.append(subprocess.Popen(
                    ["ssh", "-p", str(args.ssh_port), host, remote]))
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        return 130


if __name__ == "__main__":
    sys.exit(main())
