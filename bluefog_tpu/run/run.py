"""``bfrun``: process launcher for multi-host runs.

Parity: reference ``bluefog/run/run.py`` (``bfrun -np N -H h1:4,h2:4 python
train.py`` composing an ``mpirun`` command).  The TPU-native launcher has no
MPI: processes rendezvous through JAX's distributed coordinator
(``jax.distributed.initialize``), which rides gRPC over DCN — the same service
TPU pods use natively.

Modes
-----
* Local fan-out (testing / CPU):
    python -m bluefog_tpu.run -np 4 python train.py
  spawns 4 processes on this machine wired to a local coordinator; each sets
  ``BFTPU_*`` env consumed by ``bf.init_distributed()``.
* Multi-host (reference ``-H host:slots`` flag, ``run/run.py:58-118``):
    python -m bluefog_tpu.run -np 8 -H tpu-host-0:4,tpu-host-1:4 python train.py
  launches ``slots`` processes per host via ssh (slot-major rank order, like
  mpirun ``-map-by slot``) with the coordinator on the first host.  A bare
  hostname means one slot.
* TPU pod slices: run the same command on every host (GKE/xmanager style);
  ``bf.init_distributed()`` with no env auto-detects the TPU pod coordinator.
"""

from __future__ import annotations

import argparse
import functools
import os
import shlex
import socket
import subprocess
import sys
import threading
import time
import uuid

__all__ = ["main", "build_parser", "parse_hosts", "virtual_mesh_env"]


def virtual_mesh_env(env: dict, num_devices: int) -> dict:
    """Mutate ``env`` so a child Python sees ``num_devices`` virtual CPU
    devices (testing mode shared by ``bfrun --devices-per-proc`` and
    ``ibfrun -np``).  Must land before JAX loads in the child — XLA reads
    the device-count flag at backend init."""
    env["BFTPU_LOCAL_DEVICES"] = str(num_devices)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count="
                        f"{num_devices}")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def parse_hosts(spec: str, num_proc: int):
    """Expand ``h1:4,h2:4`` into a rank-ordered list of (host, local_rank).

    Mirrors the reference launcher's host-slot parsing (``run/run.py:58-118``):
    each entry contributes ``slots`` consecutive ranks (mpirun ``-map-by
    slot``), bare hostnames count as one slot, and the total slot count must
    cover ``num_proc``.
    """
    entries = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        host, sep, slots_s = item.partition(":")
        if not host:
            raise ValueError(f"bad host entry {item!r}")
        if sep:
            try:
                slots = int(slots_s)
            except ValueError:
                raise ValueError(f"bad slot count in {item!r}") from None
            if slots <= 0:
                raise ValueError(f"slot count must be positive in {item!r}")
        else:
            slots = 1
        entries.append((host, slots))
    total = sum(s for _, s in entries)
    if total < num_proc:
        raise ValueError(
            f"host slots ({total}) < requested processes ({num_proc})")
    placement = []
    next_local = {}  # repeated host entries keep accumulating local ranks
    for host, slots in entries:
        for _ in range(slots):
            if len(placement) == num_proc:
                break
            local_rank = next_local.get(host, 0)
            next_local[host] = local_rank + 1
            placement.append((host, local_rank))
    return placement


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


_TAG_LOCK = threading.Lock()


def _spawn_tagged(cmd_or_argv, env, rank: int):
    """Popen with pump threads that prefix each output line with ``[rank]``
    (mpirun ``--tag-output`` parity: stdout stays stdout, stderr stays
    stderr).  Whole lines are written under one lock, so ranks can no
    longer tear each other's lines on the shared streams.  The threads are
    joined by ``_join_tag_pumps`` after the child exits — they must drain
    the pipes fully or trailing output would be lost at interpreter
    shutdown; ``errors='replace'`` keeps one bad byte (native crash dumps)
    from killing a pump and deadlocking the child on a full pipe."""
    p = subprocess.Popen(cmd_or_argv, env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, bufsize=1, errors="replace")

    def pump(stream, sink):
        for line in stream:
            if not line.endswith("\n"):
                line += "\n"  # unterminated final write: keep tags per-line
            with _TAG_LOCK:
                sink.write(f"[{rank}]{line}")
                sink.flush()
        stream.close()

    threads = [
        threading.Thread(target=pump, args=(p.stdout, sys.stdout),
                         daemon=True, name=f"bfrun-tag-{rank}"),
        threading.Thread(target=pump, args=(p.stderr, sys.stderr),
                         daemon=True, name=f"bfrun-tag-err-{rank}"),
    ]
    for t in threads:
        t.start()
    p._bf_tag_threads = threads
    return p


def _join_tag_pumps(entries, timeout: float = 10.0) -> None:
    """Drain tagged-output pumps after their children exited."""
    for p, _, _ in entries:
        for t in getattr(p, "_bf_tag_threads", ()):
            t.join(timeout=timeout)


# Env vars forwarded to remote ranks (the remote login shell supplies the
# rest, as with mpirun's -x lists).
_ENV_EXPORT_PREFIXES = ("BFTPU_", "XLA_", "JAX_", "BLUEFOG")


@functools.lru_cache(maxsize=None)
def _local_addrs() -> frozenset:
    addrs = {"127.0.0.1", "::1"}
    try:
        addrs.update(socket.gethostbyname_ex(socket.gethostname())[2])
    except OSError:
        pass
    return frozenset(addrs)


@functools.lru_cache(maxsize=None)
def is_local_host(host: str) -> bool:
    """True when ``host`` names THIS machine — by shortname, FQDN, or any
    address that resolves to a local interface.  A --hosts entry naming
    the local machine by FQDN/IP must not be treated as remote: bfrun
    would ssh-to-self needlessly, and ibfrun --hosts would refuse to
    start ('the first --hosts entry must be this machine')."""
    if host in ("127.0.0.1", "::1", "localhost",
                socket.gethostname(), socket.getfqdn()):
        return True
    try:
        resolved = {ai[4][0] for ai in socket.getaddrinfo(host, None)}
    except OSError:
        return False
    return bool(resolved & _local_addrs())


def rsh_argv(rsh_opt, ssh_port: int) -> list:
    """The remote transport argv prefix: ``--rsh`` override or ssh."""
    return shlex.split(rsh_opt) if rsh_opt else ["ssh", "-p", str(ssh_port)]


# Secrets must NEVER ride a remote command line: argv is world-readable in
# /proc on every gang machine for the whole session.  These keys are
# excluded from remote_run_cmd's inline exports; their owners ship them out
# of band (ibfrun pipes the gang token over the rsh client's stdin).
_ENV_NEVER_INLINE = ("BFTPU_IBF_TOKEN",)


def remote_run_cmd(env: dict, cmd: list) -> str:
    """The shell line a remote rank executes: replicate cwd + the BFTPU/JAX
    env, then the command.  Shared by bfrun and multi-machine ibfrun so a
    new env var cannot reach one launcher's remote ranks and not the
    other's."""
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items()
                       if k.startswith(_ENV_EXPORT_PREFIXES)
                       and k not in _ENV_NEVER_INLINE)
    return (f"cd {shlex.quote(os.getcwd())} && {exports} "
            + " ".join(shlex.quote(c) for c in cmd))


def _launch_shell(tag: str, rank: int, run_cmd: str,
                  piddir: str = "/tmp") -> str:
    """The remote launch command for one gang rank.

    ``setsid`` puts the rank in its own session, so the shell's PID (written
    to the tag pidfile) is the process-group id of every descendant;
    ``_remote_signal`` kills the whole group.  A bare ``pkill -f tag`` would
    only reach this shell — the training process carries no tag in its argv.
    ``-w`` (wait) is load-bearing: when the invoking remote shell is already
    a process-group leader, ``setsid`` FORKS and without ``-w`` the parent
    exits 0 immediately — the gang supervisor would read every remote rank
    as instantly successful.  The traps remove the pidfile on normal exit
    and on TERM, so healthy runs leave no litter; the KILL path cleans up
    via ``_remote_signal``."""
    pidfile = shlex.quote(f"{piddir}/{tag}.{rank}.pid")
    inner = (f"echo $$ > {pidfile}; "
             f"trap 'rm -f {pidfile}; exit 143' TERM INT; "
             f"trap 'rm -f {pidfile}' EXIT; " + run_cmd)
    return f"setsid -w sh -c {shlex.quote(inner)}"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bfrun", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-np", "--num-proc", type=int, required=True,
                   help="number of processes to launch")
    p.add_argument("-H", "--hosts", default=None,
                   help="comma-separated host[:slots] entries "
                        "(default: all local)")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--rsh", default=None,
                   help="remote-shell command used to reach -H hosts, "
                        "invoked as '<rsh> <host> <script>' (default: "
                        "'ssh -p <ssh-port>').  The same transport carries "
                        "launch, TERM/KILL escalation and pidfile cleanup, "
                        "so tests and rsh-like schedulers exercise the "
                        "REAL remote code path (reference verifies its ssh "
                        "transport live, run/run.py:128-145)")
    p.add_argument("--coordinator-port", type=int, default=None)
    p.add_argument("--devices-per-proc", type=int, default=None,
                   help="virtual CPU devices per process (testing)")
    p.add_argument("--restarts", type=int, default=0,
                   help="gang-restart budget: when any process exits "
                        "nonzero, kill the rest and relaunch ALL processes "
                        "(pair with utils.elastic.run_elastic in the "
                        "program so the job resumes from its newest "
                        "checkpoint)")
    p.add_argument("--timeline", default=None,
                   help="timeline file prefix (sets BLUEFOG_TIMELINE)")
    p.add_argument("--telemetry", action="store_true",
                   help="enable the runtime telemetry registry in every "
                        "rank (sets BLUEFOG_TPU_TELEMETRY=1 for the gang; "
                        "read it back via bf.telemetry_snapshot() or pair "
                        "with --telemetry-port for live /metrics)")
    p.add_argument("--telemetry-port", type=int, default=None,
                   metavar="BASE",
                   help="serve /metrics + /healthz per rank: rank r binds "
                        "port BASE + r (0 = ephemeral everywhere; implies "
                        "--telemetry)")
    p.add_argument("--profile", action="store_true",
                   help="enable the distributed step profiler in every "
                        "rank (sets BLUEFOG_TPU_PROFILE=1; implies "
                        "--telemetry): periodic synced step samples, "
                        "phase latency histograms and cross-rank "
                        "straggler reports every BLUEFOG_TPU_PROFILE_EVERY "
                        "steps — pair with --timeline and `python -m "
                        "bluefog_tpu.tools trace-merge` for a merged "
                        "per-rank trace")
    p.add_argument("--elastic", action="store_true",
                   help="coordinator-free gang bootstrap (ops/gang.py): "
                        "pre-assign one window-transport port per rank, "
                        "export the complete endpoint list to every rank "
                        "as BFTPU_GANG_PEERS, and enable "
                        "BLUEFOG_TPU_ELASTIC_JOIN (+ BLUEFOG_TPU_CHURN) — "
                        "membership and bootstrap ride the gossip-"
                        "replicated endpoint directory, so no process "
                        "(rank 0 included) is a bootstrap single point of "
                        "failure.  The program should call "
                        "bf.gang.init_elastic() instead of relying on the "
                        "jax coordinator")
    p.add_argument("--join", default=None, metavar="TARGET",
                   help="launch ONE process that JOINS a live gang "
                        "(requires -np 1): TARGET is any live member's "
                        "window-transport endpoint host:port, or "
                        "@<prefix> naming a persisted gang-directory "
                        "prefix (BLUEFOG_TPU_GANG_DIR_PATH) whose live "
                        "members are tried in turn.  With "
                        "--devices-per-proc N, N is the WORLD rank count "
                        "(the joiner sees the whole virtual mesh).  "
                        "Exported to the child as BFTPU_GANG_JOIN; the "
                        "program calls bf.gang.join_gang()")
    p.add_argument("--join-want", type=int, default=None, metavar="N",
                   help="with --join/--grow: how many vacant ranks the "
                        "joining process claims (default 1; a replacement "
                        "for a multi-rank process should claim its whole "
                        "seat count).  Exported as BFTPU_GANG_JOIN_WANT")
    p.add_argument("--grow", type=float, default=None, metavar="SECONDS",
                   help="spawn one extra joining process SECONDS after "
                        "launch (requires --elastic): the late process "
                        "gets BFTPU_GANG_JOIN=@<gang-dir> and is "
                        "supervised like any gang rank — its exit reason "
                        "appears in the gang summary")
    p.add_argument("--gang-dir", default=None, metavar="PREFIX",
                   help="gang-directory persistence prefix "
                        "(BLUEFOG_TPU_GANG_DIR_PATH); default with "
                        "--elastic: a fresh /tmp prefix per incarnation")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="fault-injection spec for the gang (utils/chaos.py "
                        "grammar): comma-separated kill:rank=K:step=N / "
                        "delay:rank=K:step=N[:steps=M][:ms=D] / "
                        "partition:rank=K:step=N[:steps=M].  Exported to "
                        "every rank as BLUEFOG_TPU_CHAOS (ranks self-inject "
                        "at the named steps) and implies BLUEFOG_TPU_CHURN=1 "
                        "so the survivors re-form; a chaos-killed rank's "
                        "death does NOT trigger the normal "
                        "any-failure-kills-the-gang policy")
    p.add_argument("--tag-output", action="store_true",
                   help="prefix every output line with [rank] (mpirun "
                        "--tag-output parity); also prevents ranks' lines "
                        "interleaving mid-line on the shared stdout")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program to launch")
    return p


def _child_env(args, coord: str, rank: int, local_rank: int = 0,
               local_size: int = 1, gang_peers: str = None,
               gang_dir: str = None, join_target: str = None,
               join_world: int = None) -> dict:
    env = dict(os.environ)
    env["BFTPU_COORDINATOR"] = coord
    env["BFTPU_NUM_PROCESSES"] = str(args.num_proc)
    env["BFTPU_PROCESS_ID"] = str(rank)
    env["BFTPU_LOCAL_ID"] = str(local_rank)
    env["BFTPU_LOCAL_SIZE"] = str(local_size)
    elastic = gang_peers is not None or join_target is not None
    if args.devices_per_proc:
        if elastic:
            # Elastic/join processes see the WHOLE virtual world (rank
            # ownership is per-process through the gang directory, not
            # through jax.distributed's device spanning): each founding
            # member of a 4-rank gang forges 4 virtual devices, not 1.
            # For a top-level --join, --devices-per-proc NAMES the world
            # size; a --grow joiner inherits the gang's (join_world).
            if join_target is not None:
                n = join_world or args.devices_per_proc
            else:
                n = args.num_proc * args.devices_per_proc
            virtual_mesh_env(env, n)
        else:
            virtual_mesh_env(env, args.devices_per_proc)
    if elastic:
        env.setdefault("BLUEFOG_TPU_ELASTIC_JOIN", "1")
        env.setdefault("BLUEFOG_TPU_CHURN", "1")
        if gang_dir:
            env.setdefault("BLUEFOG_TPU_GANG_DIR_PATH", gang_dir)
    if gang_peers is not None:
        env["BFTPU_GANG_PEERS"] = gang_peers
    if join_target is not None:
        env["BFTPU_GANG_JOIN"] = join_target
        if getattr(args, "join_want", None):
            env["BFTPU_GANG_JOIN_WANT"] = str(args.join_want)
    if args.timeline:
        env["BLUEFOG_TIMELINE"] = args.timeline
    if args.telemetry or args.telemetry_port is not None or args.profile:
        env["BLUEFOG_TPU_TELEMETRY"] = "1"
    if args.profile:
        env["BLUEFOG_TPU_PROFILE"] = "1"
    if args.telemetry_port is not None:
        # Distinct port per rank (0 = ephemeral for every rank; the bound
        # port is logged by the endpoint at init).
        env["BLUEFOG_TPU_TELEMETRY_PORT"] = str(
            args.telemetry_port + rank if args.telemetry_port else 0)
    if args.chaos and join_target is None:
        # Ranks self-inject (the launcher cannot know when "step N"
        # happens); chaos without the churn controller would just be a
        # crashed gang, so --chaos implies churn unless explicitly pinned.
        env["BLUEFOG_TPU_CHAOS"] = args.chaos
        env.setdefault("BLUEFOG_TPU_CHURN", "1")
    if join_target is not None:
        # A replacement spawned into a chaos gang must NOT re-execute the
        # fault that vacated its seat: a joiner adopting the killed
        # rank's id would otherwise SIGKILL itself at the same step.
        env.pop("BLUEFOG_TPU_CHAOS", None)
        if args.chaos:
            env.setdefault("BLUEFOG_TPU_CHURN", "1")
    return env


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("bfrun: no command given", file=sys.stderr)
        return 2
    if args.num_proc < 1:
        print("bfrun: -np must be >= 1", file=sys.stderr)
        return 2

    if args.join is not None and args.num_proc != 1:
        print("bfrun: --join launches exactly one joining process; "
              "use -np 1", file=sys.stderr)
        return 2
    if args.grow is not None and not args.elastic:
        print("bfrun: --grow requires --elastic (the joiner bootstraps "
              "from the gang directory)", file=sys.stderr)
        return 2

    if args.hosts:
        try:
            placement = parse_hosts(args.hosts, args.num_proc)
        except ValueError as e:
            print(f"bfrun: {e}", file=sys.stderr)
            return 2
    else:
        placement = [("127.0.0.1", i) for i in range(args.num_proc)]

    if args.grow is not None and args.gang_dir is None \
            and any(not is_local_host(h) for h, _ in placement):
        # The default gang-dir is a launcher-local /tmp prefix, but
        # remote members persist their replicas on THEIR hosts — the
        # locally-spawned joiner would find nothing and its failure
        # would tear down the healthy gang.
        print("bfrun: --grow with remote hosts needs --gang-dir on "
              "storage shared with this machine (the joiner bootstraps "
              "from the persisted directory replicas)", file=sys.stderr)
        return 2

    tolerate = frozenset()
    if args.chaos:
        from bluefog_tpu.utils.chaos import killed_ranks, parse_chaos
        try:
            faults = parse_chaos(args.chaos)
        except ValueError as e:
            print(f"bfrun: {e}", file=sys.stderr)
            return 2
        bad_targets = [f.rank for f in faults if f.rank >= args.num_proc]
        if bad_targets:
            print(f"bfrun: --chaos targets rank(s) {sorted(bad_targets)} "
                  f"outside the {args.num_proc}-process gang",
                  file=sys.stderr)
            return 2
        tolerate = frozenset(killed_ranks(faults))

    # The remote transport: one argv prefix for launch AND signalling.
    rsh = rsh_argv(args.rsh, args.ssh_port)

    host_slots = {}
    for host, _ in placement:
        host_slots[host] = host_slots.get(host, 0) + 1

    attempt = 0
    while True:
        # Fresh coordinator port per incarnation (unless pinned): the old
        # coordinator died with rank 0 and its port may sit in TIME_WAIT.
        port = args.coordinator_port or _free_port()
        coord = f"{placement[0][0]}:{port}"
        # Unique per-incarnation tag: exported into every child env, so it
        # appears on remote command lines and `pkill -f <tag>` can reach
        # ranks whose local ssh client we can only disconnect, not signal.
        tag = f"bfrun-gang-{uuid.uuid4().hex[:12]}"
        gang_peers = None
        gang_dir = args.gang_dir
        if args.elastic:
            # One pinned window-transport port per rank, exported to the
            # whole gang: with the complete endpoint map known at launch
            # there is no key-value exchange to run and no coordinator to
            # lose — gossip anti-entropy keeps the map live from here on.
            # (Ports are probed free locally; for remote hosts the probe
            # is best-effort — a collision surfaces as that rank failing
            # to bind, which the restart budget covers.)
            win_ports = [_free_port() for _ in placement]
            gang_peers = ",".join(
                f"{host}:{p}" for (host, _), p in zip(placement, win_ports))
            if gang_dir is None:
                import tempfile
                gang_dir = os.path.join(
                    tempfile.mkdtemp(prefix="bf-gang-"), "gang")
        if args.join is not None and gang_dir is None \
                and args.join.startswith("@"):
            gang_dir = args.join[1:]
        entries = []  # (Popen, host, is_remote)

        def _spawn_member(rank, host, env):
            env["BFTPU_GANG_TAG"] = tag
            if is_local_host(host):
                proc = (_spawn_tagged(cmd, env, rank) if args.tag_output
                        else subprocess.Popen(cmd, env=env))
                entries.append((proc, host, False))
            else:
                remote = _launch_shell(tag, rank, remote_run_cmd(env, cmd))
                rsh_cmd = rsh + [host, remote]
                proc = (_spawn_tagged(rsh_cmd, None, rank)
                        if args.tag_output
                        else subprocess.Popen(rsh_cmd))
                entries.append((proc, host, True))

        grow = []
        if args.grow is not None:
            def _spawn_joiner():
                rank = len(entries)
                env = _child_env(args, coord, rank, 0, 1,
                                 gang_dir=gang_dir,
                                 join_target=f"@{gang_dir}",
                                 join_world=args.num_proc
                                 * (args.devices_per_proc or 1))
                print(f"bfrun: growing the gang — spawning a joining "
                      f"process as rank {rank} (@{gang_dir})",
                      file=sys.stderr)
                _spawn_member(rank, "127.0.0.1", env)
            grow = [(time.monotonic() + args.grow, _spawn_joiner)]
        try:
            for rank, (host, local_rank) in enumerate(placement):
                env = _child_env(args, coord, rank, local_rank,
                                 host_slots[host], gang_peers=gang_peers,
                                 gang_dir=gang_dir,
                                 join_target=args.join)
                _spawn_member(rank, host, env)
            rc = _wait_gang(entries, rsh, tag, tolerate=tolerate,
                            grow=grow)
        except KeyboardInterrupt:
            print("bfrun: interrupted; stopping the gang", file=sys.stderr)
            _kill_gang(entries, rsh, tag)
            return 130
        if rc == 0 or attempt >= args.restarts:
            return rc
        attempt += 1
        # Backoff so a deterministically-failing command (bad flag, missing
        # module, pinned port in TIME_WAIT) cannot burn the budget in a
        # tight loop.
        delay = min(10.0, 2.0 ** (attempt - 1))
        print(f"bfrun: process failed (exit {rc}); restarting the gang "
              f"in {delay:.0f}s (attempt {attempt}/{args.restarts})",
              file=sys.stderr)
        time.sleep(delay)


def _remote_signal(host: str, rsh: list, tag: str, sig: str) -> None:
    """Signal every remote process group of this gang tag (killing the
    local ssh client only drops the connection; without a TTY the remote
    command keeps running).

    Each rank's launch shell ran under ``setsid`` and wrote its PID — the
    group id of all its descendants — to ``/tmp/<tag>.<rank>.pid``, so
    ``kill -- -PGID`` reaches the training process even though its argv
    carries no tag.  A ``pkill -f`` fallback covers shells that have not
    reached the pidfile write.  EVERY occurrence of the tag in this command
    brackets its first character (``[b]frun-...``): as a glob that still
    matches the literal pidfile paths, and as the pkill regex it still
    matches the launch shells' command lines — but this kill shell's own
    cmdline now contains only bracketed forms, which the regex does not
    match, so the kill shell never signals itself mid-cleanup.  KILL also
    removes the pidfiles (TERM leaves them for the launch shells' own
    TERM/EXIT traps)."""
    btag = f"[{tag[0]}]{tag[1:]}"
    cleanup = f"rm -f /tmp/{btag}.*.pid; " if sig == "KILL" else ""
    # `kill -s SIG -- -PGID` is the POSIX form: dash's builtin rejects the
    # `kill -SIG -- -PGID` spelling ("Illegal number").
    script = (
        f"for f in /tmp/{btag}.*.pid; do "
        f"[ -f \"$f\" ] && kill -s {sig} -- -\"$(cat \"$f\")\" 2>/dev/null; "
        f"done; {cleanup}pkill -{sig} -f {shlex.quote(btag)}; true")
    subprocess.run(
        rsh + [host, script],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, timeout=30,
        check=False)


def _exit_reason(rc) -> str:
    """Human-readable exit reason for one gang process."""
    if rc is None:
        return "UNRESPONSIVE (still running after SIGKILL)"
    if rc < 0:
        import signal as _signal
        try:
            name = _signal.Signals(-rc).name
        except ValueError:
            name = f"signal {-rc}"
        return f"killed by {name}"
    return f"exit {rc}"


def _kill_gang(entries, rsh: list, tag: str,
               kill_grace: float = 10.0) -> None:
    """TERM the whole gang (local + remote), escalate to KILL after
    ``kill_grace`` — a peer blocked in a collective against a dead rank
    with ``run_elastic``'s SIGTERM handler installed can never reach a step
    boundary to honor TERM — and print a per-rank exit-reason summary, so
    a hung remote shell (whose local rsh client we can only disconnect)
    can never leave the gang half-dead SILENTLY: any rank the escalation
    could not reap is called out as UNRESPONSIVE."""
    remote_hosts = sorted({h for _, h, r in entries if r})
    for p, _, _ in entries:
        if p.poll() is None:
            p.terminate()
    for h in remote_hosts:
        _remote_signal(h, rsh, tag, "TERM")
    deadline = time.monotonic() + kill_grace
    escalated = set()
    for rank, (p, _, _) in enumerate(entries):
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            escalated.add(rank)
            p.kill()
    for h in remote_hosts:
        _remote_signal(h, rsh, tag, "KILL")
    for rank, (p, _, _) in enumerate(entries):
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass
    parts = []
    for rank, (p, host, is_remote) in enumerate(entries):
        reason = _exit_reason(p.poll())
        if rank in escalated:
            reason += " after SIGTERM timeout"
        if is_remote:
            reason += f" [{host}]"
        parts.append(f"rank {rank}: {reason}")
    print("bfrun: gang exit summary — " + "; ".join(parts),
          file=sys.stderr)


def _wait_gang(entries, rsh: list, tag: str,
               tolerate=frozenset(), grow=()) -> int:
    """Wait for all processes; any nonzero exit kills the survivors —
    except ranks in ``tolerate`` (chaos-injected deaths), whose exits are
    expected and must leave the survivors running so recovery can be
    observed.  The gang still waits for EVERY process to finish.

    The gang may GROW mid-wait (elastic scale-up): ``grow`` is a list of
    ``(fire_monotonic, spawn_fn)`` entries; when an entry's time comes,
    its ``spawn_fn`` appends a new ``(proc, host, is_remote)`` member to
    ``entries`` and from then on the joined process is supervised exactly
    like a founding rank — its nonzero exit kills the gang and its exit
    reason appears in the summary (mirroring the kill-toleration the loop
    already has for shrink)."""
    pending_grow = sorted(grow, key=lambda g: g[0])
    while True:
        while pending_grow and time.monotonic() >= pending_grow[0][0]:
            _, spawn_fn = pending_grow.pop(0)
            try:
                spawn_fn()  # appends to `entries`; supervised below
            except Exception as e:  # noqa: BLE001 — a failed grow is fatal
                print(f"bfrun: failed to grow the gang: {e}",
                      file=sys.stderr)
                _kill_gang(entries, rsh, tag)
                _join_tag_pumps(entries)
                return 1
        rcs = [p.poll() for p, _, _ in entries]
        bad = next((r for i, r in enumerate(rcs)
                    if r not in (None, 0) and i not in tolerate), None)
        if bad is None:
            if all(r is not None for r in rcs):
                if pending_grow:
                    # Every rank already finished cleanly: there is no
                    # gang left to grow into — spawning the joiner now
                    # would only manufacture a failure.
                    print(f"bfrun: gang finished before "
                          f"{len(pending_grow)} scheduled --grow "
                          "spawn(s); skipping them", file=sys.stderr)
                _join_tag_pumps(entries)
                return 0
            time.sleep(0.2)
            continue
        _kill_gang(entries, rsh, tag)
        _join_tag_pumps(entries)
        return bad


if __name__ == "__main__":
    sys.exit(main())
