"""``ibfrun``: interactive sessions on the TPU mesh.

Parity: reference ``run/interactive_run.py:34-90`` — ``ibfrun start -np 4``
boots an ipcontroller plus mpirun'd ipengines so a notebook can drive the MPI
world, paired with ``bf.suspend()/bf.resume()`` to park the background thread
between cells.

The TPU rebuild is single-controller SPMD: ONE Python process drives every
device, so there is no engine fleet to boot and no ipyparallel dependency —
any Jupyter kernel or plain REPL that imports ``bluefog_tpu`` *is* the
interactive mode.  What this launcher adds is the environment bootstrap the
reference's ``ibfrun start`` performed:

* ``ibfrun`` — drop into an IPython (fallback: ``python -i``) shell with
  ``bf`` imported and ``bf.init()`` already run over the real devices.
* ``ibfrun -np 8`` — same, over a virtual 8-device CPU mesh (the testing
  topology-development loop; XLA device-count flags must be set before JAX
  loads, which is exactly why this is a launcher and not a helper function).
* ``ibfrun -np 8 jupyter notebook`` (any command) — run that command inside
  the prepared environment instead of a REPL; kernels started by it inherit
  the virtual mesh.

Inside the session, ``bf.suspend()`` / ``bf.resume()`` quiesce and re-enable
communication between cells (reference ``common/basics.py:497-515``).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap

from bluefog_tpu.run.run import virtual_mesh_env

__all__ = ["main", "build_parser"]

_BOOT = ("import bluefog_tpu as bf; bf.init(); "
         "print('bluefog_tpu interactive: %d rank(s) ready; "
         "bf.suspend()/bf.resume() park the session' % bf.size())")
# Site hooks can pin jax_platforms via jax.config, which env vars don't
# override — force it the way tests/conftest.py does.
_BOOT_CPU = "import jax; jax.config.update('jax_platforms', 'cpu'); " + _BOOT


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ibfrun", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="virtual CPU device count (default: real devices)")
    p.add_argument("--no-init", action="store_true",
                   help="prepare the environment but skip bf.init()")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run instead of a REPL")
    return p


def _cpu_pin_dir() -> str:
    """A dir whose ``sitecustomize`` pins ``jax_platforms`` to cpu in every
    Python child — env vars alone lose to site hooks that pin the platform
    via ``jax.config`` (e.g. TPU-VM images), and command mode (``ibfrun -np 8
    jupyter notebook``) has no boot string to do it in-process.  The shim
    chains to the environment's own sitecustomize first."""
    d = tempfile.mkdtemp(prefix="bf-ibfrun-")
    with open(os.path.join(d, "sitecustomize.py"), "w") as f:
        f.write(textwrap.dedent("""\
            import os as _os, sys as _sys
            _d = _os.path.dirname(_os.path.abspath(__file__))
            _sys.path = [p for p in _sys.path
                         if _os.path.abspath(p or '.') != _d]
            _sys.modules.pop('sitecustomize', None)
            try:
                import sitecustomize  # noqa: F401 — the environment's own
            except ImportError:
                pass
            _sys.path.insert(0, _d)
            try:
                import jax
                jax.config.update('jax_platforms', 'cpu')
            except Exception:
                pass
            """))
    return d


def _prepared_env(num_proc):
    """Returns ``(env, pin_dir)``; ``pin_dir`` (or None) is owned by the
    caller, which must remove it after the child exits — it is deliberately
    NOT carried in the environment, where a nested ibfrun would inherit and
    delete its parent session's live pin directory."""
    env = dict(os.environ)
    pin = None
    if num_proc:
        virtual_mesh_env(env, num_proc)
        pin = _cpu_pin_dir()
        env["PYTHONPATH"] = pin + os.pathsep + env.get("PYTHONPATH", "")
    return env, pin


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    env, pin = _prepared_env(args.num_proc)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]

    try:
        if cmd:
            return subprocess.call(cmd, env=env)

        boot = "" if args.no_init else (_BOOT_CPU if args.num_proc else _BOOT)
        if shutil.which("ipython"):
            argv = ["ipython", "-i", "-c", boot] if boot else ["ipython"]
        else:
            argv = [sys.executable, "-i"] + (["-c", boot] if boot else [])
        return subprocess.call(argv, env=env)
    finally:
        if pin:
            shutil.rmtree(pin, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
