"""``ibfrun``: interactive sessions on the TPU mesh.

Parity: reference ``run/interactive_run.py:34-90`` — ``ibfrun start -np 4``
boots an ipcontroller plus mpirun'd ipengines so a notebook can drive the MPI
world, paired with ``bf.suspend()/bf.resume()`` to park the background thread
between cells.

The TPU rebuild is single-controller SPMD: ONE Python process drives every
device, so there is no engine fleet to boot and no ipyparallel dependency —
any Jupyter kernel or plain REPL that imports ``bluefog_tpu`` *is* the
interactive mode.  What this launcher adds is the environment bootstrap the
reference's ``ibfrun start`` performed:

* ``ibfrun`` — drop into an IPython (fallback: ``python -i``) shell with
  ``bf`` imported and ``bf.init()`` already run over the real devices.
* ``ibfrun -np 8`` — same, over a virtual 8-device CPU mesh (the testing
  topology-development loop; XLA device-count flags must be set before JAX
  loads, which is exactly why this is a launcher and not a helper function).
* ``ibfrun -np 8 jupyter notebook`` (any command) — run that command inside
  the prepared environment instead of a REPL; kernels started by it inherit
  the virtual mesh.
* ``ibfrun -np 4 --hosts h1:2,h2:2`` — MULTI-MACHINE interactive mode
  (reference ``interactive_run.py:271-420`` ``multiple_machines_launch``):
  ranks 1..n-1 run exec-loop workers launched over the same ``--rsh``/ssh
  transport as ``bfrun``, rank 0 is a REPL that ships every complete cell
  to the fleet before running it locally, so collectives inside a cell run
  SPMD across the gang (``run/cluster_repl.py``).  With ``--hosts``, ``-np``
  counts processes (as in bfrun) and ``--devices-per-proc`` adds a virtual
  mesh per process.
* ``ibfrun -np 4 --hosts h1:2,h2:2 --kernel-file /tmp/bf-kernel.json`` —
  multi-machine JUPYTER mode: rank 0 becomes a real ipykernel in front of
  the same cell-shipping channel; connect any notebook/console client to
  the connection file and every executed cell drives the whole gang (the
  reference's ipcontroller+ipengines role).  See
  ``examples/cluster_notebook.ipynb``.

Inside the session, ``bf.suspend()`` / ``bf.resume()`` quiesce and re-enable
communication between cells (reference ``common/basics.py:497-515``).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap
import time
import uuid

from bluefog_tpu.run.run import virtual_mesh_env

__all__ = ["main", "build_parser"]

_BOOT = ("import bluefog_tpu as bf; bf.init(); "
         "print('bluefog_tpu interactive: %d rank(s) ready; "
         "bf.suspend()/bf.resume() park the session' % bf.size())")
# Site hooks can pin jax_platforms via jax.config, which env vars don't
# override — force it the way tests/conftest.py does.
_BOOT_CPU = "import jax; jax.config.update('jax_platforms', 'cpu'); " + _BOOT


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ibfrun", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="virtual CPU device count; with --hosts: number of "
                        "processes (bfrun semantics)")
    p.add_argument("--no-init", action="store_true",
                   help="prepare the environment but skip bf.init()")
    p.add_argument("-H", "--hosts", default=None,
                   help="multi-machine mode: comma-separated host[:slots] "
                        "entries; rank 0 is the local REPL, the rest are "
                        "exec-loop workers")
    p.add_argument("--rsh", default=None,
                   help="remote-shell command for --hosts workers "
                        "(default: ssh -p <ssh-port>).  Must forward "
                        "stdin to the remote command like ssh does — the "
                        "per-gang auth token travels that way, never on "
                        "a command line")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--devices-per-proc", type=int, default=None,
                   help="virtual CPU devices per process (--hosts mode)")
    p.add_argument("--kernel-file", default=None,
                   help="--hosts mode: run rank 0 as a JUPYTER KERNEL "
                        "writing this connection file instead of a line "
                        "REPL — connect a notebook client to it and every "
                        "executed cell runs SPMD on the whole "
                        "multi-machine gang")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run instead of a REPL")
    return p


def _cluster(args) -> int:
    """Launch the multi-machine interactive gang: rank-0 REPL locally, the
    other ranks as cluster_repl workers over the rsh/ssh transport (the
    launch/kill/env machinery is bfrun's — one remote code path to trust)."""
    from bluefog_tpu.run import run as R
    n = args.num_proc or 1
    placement = R.parse_hosts(args.hosts, n)
    coord_host = placement[0][0]
    if not R.is_local_host(coord_host):
        # Rank 0 (REPL + coordinator + control socket) always runs HERE;
        # fail fast instead of letting workers dial a host where nothing
        # listens and time out opaquely two minutes later.
        print(f"ibfrun: the first --hosts entry ({coord_host}) must be this "
              "machine — rank 0 is the local REPL", file=sys.stderr)
        return 2
    rsh = R.rsh_argv(args.rsh, args.ssh_port)
    coord = f"{coord_host}:{R._free_port()}"
    ctrl = f"{coord_host}:{R._free_port()}"
    tag = f"ibfrun-gang-{uuid.uuid4().hex[:12]}"
    # Per-gang shared secret: workers exec() shipped cells, so both sides
    # of the control channel prove possession via an HMAC challenge-
    # response at connect time (cluster_repl handshake); rides
    # remote_run_cmd's BFTPU_ env replication.
    import secrets
    token = secrets.token_hex(16)
    host_slots = {}
    for host, _ in placement:
        host_slots[host] = host_slots.get(host, 0) + 1

    def child_env(rank, local_rank, local_size):
        env = dict(os.environ)
        env["BFTPU_COORDINATOR"] = coord
        env["BFTPU_NUM_PROCESSES"] = str(n)
        env["BFTPU_PROCESS_ID"] = str(rank)
        env["BFTPU_LOCAL_ID"] = str(local_rank)
        env["BFTPU_LOCAL_SIZE"] = str(local_size)
        env["BFTPU_GANG_TAG"] = tag
        env["BFTPU_IBF_TOKEN"] = token
        if args.devices_per_proc:
            virtual_mesh_env(env, args.devices_per_proc)
        return env

    wcmd = [sys.executable, "-m", "bluefog_tpu.run.cluster_repl",
            "--ctrl", ctrl]
    entries = []
    try:
        for rank, (host, local_rank) in enumerate(placement):
            if rank == 0:
                continue  # the REPL below
            env = child_env(rank, local_rank, host_slots[host])
            if R.is_local_host(host):
                # Local children get the token via the env DICT (never a
                # command line); remote ones read it from the rsh stdin
                # below — remote_run_cmd refuses to inline it into argv,
                # where /proc would expose it to every local user.
                entries.append((subprocess.Popen(wcmd, env=env), host,
                                False))
            else:
                run_cmd = ("IFS= read -r BFTPU_IBF_TOKEN && "
                           "export BFTPU_IBF_TOKEN && "
                           + R.remote_run_cmd(env, wcmd))
                remote = R._launch_shell(tag, rank, run_cmd)
                p = subprocess.Popen(rsh + [host, remote],
                                     stdin=subprocess.PIPE, text=True)
                # Register BEFORE feeding the token: a dead rsh client
                # (bad host, instant ssh failure) raises BrokenPipeError
                # on the write, and the cleanup below must reach this
                # child too.
                entries.append((p, host, True))
                p.stdin.write(token + "\n")
                p.stdin.close()
        front = (["--kernel-file", args.kernel_file] if args.kernel_file
                 else ["--repl"])
        rc = subprocess.call(
            [sys.executable, "-m", "bluefog_tpu.run.cluster_repl"] + front
            + ["--ctrl", ctrl, "--expect", str(n - 1)],
            env=child_env(0, placement[0][1], host_slots[coord_host]))
    except KeyboardInterrupt:
        print("ibfrun: interrupted; stopping the gang", file=sys.stderr)
        R._kill_gang(entries, rsh, tag)
        return 130
    except OSError as e:
        # A failing rsh client (e.g. BrokenPipeError writing the gang
        # token) must not leak the already-launched workers: kill the
        # gang, then surface the real error.
        print(f"ibfrun: gang launch failed ({e}); stopping the gang",
              file=sys.stderr)
        R._kill_gang(entries, rsh, tag)
        raise
    # REPL exit ends the session: workers exit on control-channel EOF.
    deadline = time.monotonic() + 15
    for p, _, _ in entries:
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            pass
    if any(p.poll() is None for p, _, _ in entries):
        R._kill_gang(entries, rsh, tag)
    return rc


def _cpu_pin_dir() -> str:
    """A dir whose ``sitecustomize`` pins ``jax_platforms`` to cpu in every
    Python child — env vars alone lose to site hooks that pin the platform
    via ``jax.config`` (e.g. TPU-VM images), and command mode (``ibfrun -np 8
    jupyter notebook``) has no boot string to do it in-process.  The shim
    chains to the environment's own sitecustomize first."""
    d = tempfile.mkdtemp(prefix="bf-ibfrun-")
    with open(os.path.join(d, "sitecustomize.py"), "w") as f:
        f.write(textwrap.dedent("""\
            import os as _os, sys as _sys
            _d = _os.path.dirname(_os.path.abspath(__file__))
            _sys.path = [p for p in _sys.path
                         if _os.path.abspath(p or '.') != _d]
            _sys.modules.pop('sitecustomize', None)
            try:
                import sitecustomize  # noqa: F401 — the environment's own
            except ImportError:
                pass
            _sys.path.insert(0, _d)
            try:
                import jax
                jax.config.update('jax_platforms', 'cpu')
            except Exception:
                pass
            """))
    return d


def _prepared_env(num_proc):
    """Returns ``(env, pin_dir)``; ``pin_dir`` (or None) is owned by the
    caller, which must remove it after the child exits — it is deliberately
    NOT carried in the environment, where a nested ibfrun would inherit and
    delete its parent session's live pin directory."""
    env = dict(os.environ)
    pin = None
    if num_proc:
        virtual_mesh_env(env, num_proc)
        pin = _cpu_pin_dir()
        env["PYTHONPATH"] = pin + os.pathsep + env.get("PYTHONPATH", "")
    return env, pin


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if args.hosts:
        if cmd or args.no_init:
            # The fleet protocol IS the session: an arbitrary command has
            # no cell stream to broadcast, and workers must init to
            # rendezvous.  Refuse rather than silently ignore.
            print("ibfrun: --hosts mode drives a REPL only; a command and "
                  "--no-init are not supported with it", file=sys.stderr)
            return 2
        return _cluster(args)
    if args.kernel_file:
        print("ibfrun: --kernel-file drives the multi-machine gang and "
              "needs --hosts (single-machine notebooks just start any "
              "kernel under `ibfrun -np N jupyter ...`)", file=sys.stderr)
        return 2
    env, pin = _prepared_env(args.num_proc)

    try:
        if cmd:
            return subprocess.call(cmd, env=env)

        boot = "" if args.no_init else (_BOOT_CPU if args.num_proc else _BOOT)
        if shutil.which("ipython"):
            argv = ["ipython", "-i", "-c", boot] if boot else ["ipython"]
        else:
            argv = [sys.executable, "-i"] + (["-c", boot] if boot else [])
        return subprocess.call(argv, env=env)
    finally:
        if pin:
            shutil.rmtree(pin, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
