from bluefog_tpu.run.run import main

raise SystemExit(main())
