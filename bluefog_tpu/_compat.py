"""Cross-version jax/orbax shims, installed at package import.

The codebase targets the modern ``jax.shard_map`` spelling; on jax
releases where it still lives in ``jax.experimental.shard_map`` (< 0.5)
every op would die with ``AttributeError`` at dispatch.  Alias it (with
the ``check_vma`` → ``check_rep`` kwarg rename) so one import works on
both sides of the move.  The same treatment covers the varying-manual-axes
(vma) surface the Pallas kernels use (``lax.pvary``,
``ShapeDtypeStruct(vma=...)``, ``pltpu.CompilerParams``) and the orbax
checkpoint-metadata accessor, all of which moved between the versions
this image may carry.
"""

from __future__ import annotations

import functools
import inspect

import jax


def _install_shard_map() -> None:
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:  # renamed from check_rep after the move
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, *args, **kwargs)

    jax.shard_map = shard_map


def _install_axis_size() -> None:
    if hasattr(jax.lax, "axis_size"):
        return
    from jax._src import core as _core

    def axis_size(axis_name):
        """Static size of a named mesh axis (modern ``lax.axis_size``):
        read off the ambient axis env, so it stays a python int under
        shard_map (callers use it in shape arithmetic)."""
        return _core.get_axis_env().axis_size(axis_name)

    jax.lax.axis_size = axis_size


def _install_pvary() -> None:
    """``lax.pvary`` (vma tracking, jax >= 0.6) marks a replicated value as
    varying over manual axes.  Older jax has no vma system at all — under
    ``check_rep=False`` shard_map the marker is semantically a no-op — so
    the shim is the identity.  (``lax.pcast`` callers probe for it with
    hasattr and fall back to ``pvary``, so only ``pvary`` needs to exist.)"""
    if hasattr(jax.lax, "pvary") or hasattr(jax.lax, "pcast"):
        return
    jax.lax.pvary = lambda x, axis_names: x


# Does this jax's ShapeDtypeStruct carry varying-manual-axes metadata?
_SDS_HAS_VMA = "vma" in inspect.signature(
    jax.ShapeDtypeStruct.__init__).parameters


def shape_dtype_struct(shape, dtype, vma=None) -> jax.ShapeDtypeStruct:
    """``jax.ShapeDtypeStruct`` with the ``vma=`` kwarg dropped on jax
    releases that predate vma tracking (< 0.6): there the avals carry no
    varying-axes metadata, so omitting it is exact, not an approximation.
    Used by the Pallas kernels, whose out_shape must propagate vma on
    modern jax to stay composable with ``shard_map(check_vma=True)``."""
    if _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` was spelled ``TPUCompilerParams`` before the
    jax 0.6 rename; same fields (``dimension_semantics`` et al.) on both."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def jax_ffi():
    """The jax FFI module across the namespace move, or None.

    jax >= 0.5 spells it ``jax.ffi``; 0.4.x carried it as
    ``jax.extend.ffi`` (same surface: ``ffi_call`` /
    ``register_ffi_target`` / ``pycapsule``).  Returns None on releases
    with neither — consumers (the zero-copy window put path,
    ``ops/xlaffi.py``) must treat that as "capability absent" and keep
    their host-path fallback, never raise."""
    mod = getattr(jax, "ffi", None)
    if mod is not None and hasattr(mod, "ffi_call"):
        return mod
    try:
        from jax.extend import ffi as _xffi
    except ImportError:
        return None
    return _xffi if hasattr(_xffi, "ffi_call") else None


def checkpoint_tree_metadata(checkpointer, path):
    """Tree metadata of a saved orbax checkpoint, across the metadata-API
    move: modern orbax returns a ``CheckpointMetadata`` wrapper exposing
    ``.item_metadata.tree``; 0.x returned the metadata tree directly."""
    meta = checkpointer.metadata(path)
    item = getattr(meta, "item_metadata", None)
    if item is not None:
        meta = item
    tree = getattr(meta, "tree", None)
    if tree is not None:
        meta = tree
    return meta


_install_shard_map()
_install_axis_size()
_install_pvary()
