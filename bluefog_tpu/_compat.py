"""Cross-version jax shims, installed at package import.

The codebase targets the modern ``jax.shard_map`` spelling; on jax
releases where it still lives in ``jax.experimental.shard_map`` (< 0.5)
every op would die with ``AttributeError`` at dispatch.  Alias it (with
the ``check_vma`` → ``check_rep`` kwarg rename) so one import works on
both sides of the move.
"""

from __future__ import annotations

import functools

import jax


def _install_shard_map() -> None:
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:  # renamed from check_rep after the move
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, *args, **kwargs)

    jax.shard_map = shard_map


def _install_axis_size() -> None:
    if hasattr(jax.lax, "axis_size"):
        return
    from jax._src import core as _core

    def axis_size(axis_name):
        """Static size of a named mesh axis (modern ``lax.axis_size``):
        read off the ambient axis env, so it stays a python int under
        shard_map (callers use it in shape arithmetic)."""
        return _core.get_axis_env().axis_size(axis_name)

    jax.lax.axis_size = axis_size


_install_shard_map()
_install_axis_size()
