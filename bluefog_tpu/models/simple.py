"""Small models: LeNet-5, MLP, logistic regression.

Parity: the reference's example/test workloads — LeNet for MNIST
(``examples/pytorch_mnist.py``), logistic regression and linear problems for
the optimization examples (``examples/pytorch_optimization.py``) and the
optimizer test harness (``test/torch_optimizer_test.py:100-153``).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["LeNet5", "MLP", "LogisticRegression", "LinearModel"]


class LeNet5(nn.Module):
    """Classic LeNet-5 for 28x28x1 inputs (MNIST)."""
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x, self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype)(x))
        return jnp.asarray(nn.Dense(self.num_classes, dtype=self.dtype)(x),
                           jnp.float32)


class MLP(nn.Module):
    features: Sequence[int] = (256, 256)
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x.reshape((x.shape[0], -1)), self.dtype)
        for f in self.features:
            x = nn.relu(nn.Dense(f, dtype=self.dtype)(x))
        return jnp.asarray(nn.Dense(self.num_classes, dtype=self.dtype)(x),
                           jnp.float32)


class LogisticRegression(nn.Module):
    num_classes: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        return nn.Dense(self.num_classes)(x.reshape((x.shape[0], -1)))


class LinearModel(nn.Module):
    out_features: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        return nn.Dense(self.out_features)(x)
