"""Vision Transformer (ViT-style image classifier).

Beyond the reference's CNN-only zoo (``examples/pytorch_benchmark.py``
models): a patch-embedding encoder built from the SAME transformer blocks
as ``TransformerLM`` — bidirectional attention (``TransformerConfig(
causal=False)``), so every attention implementation the LM supports
(dense, flash, ring, Ulysses) serves the vision model too, and the
parallelism strategies (dp/sp/tp/pp/ep) apply unchanged.

Structure (ViT-S/16-style defaults): Conv patchify → prepend a learned
[CLS] token → learned position embeddings → N encoder blocks → RMSNorm →
classification head on the [CLS] representation.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from bluefog_tpu.models.transformer import (TransformerConfig,
                                            block_class, local_attention)

__all__ = ["ViT"]


class ViT(nn.Module):
    """Vision transformer classifier over ``(B, H, W, C)`` images."""

    num_classes: int = 1000
    image_size: int = 224
    patch_size: int = 16
    embed_dim: int = 384
    num_layers: int = 12
    num_heads: int = 6
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    remat: bool = False
    remat_policy: str = "full"
    attn_impl: Optional[Callable] = None

    def _cfg(self) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=1,  # unused: images enter through the patch conv
            num_layers=self.num_layers, num_heads=self.num_heads,
            embed_dim=self.embed_dim, mlp_ratio=self.mlp_ratio,
            max_seq_len=(self.image_size // self.patch_size) ** 2 + 1,
            dtype=self.dtype, remat=self.remat,
            remat_policy=self.remat_policy, causal=False)

    @nn.compact
    def __call__(self, images):
        cfg = self._cfg()
        if images.shape[1] % self.patch_size or \
                images.shape[2] % self.patch_size:
            raise ValueError(
                f"image {images.shape[1]}x{images.shape[2]} not divisible "
                f"by patch size {self.patch_size}")
        x = nn.Conv(self.embed_dim,
                    kernel_size=(self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    dtype=self.dtype, name="patch_embed")(
                        jnp.asarray(images, self.dtype))
        B = x.shape[0]
        x = x.reshape(B, -1, self.embed_dim)      # (B, N_patches, d)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, self.embed_dim))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, self.embed_dim)).astype(x.dtype),
             x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(0.02),
                         (1, x.shape[1], self.embed_dim))
        x = x + pos.astype(x.dtype)
        attn = self.attn_impl if self.attn_impl is not None \
            else local_attention
        for i in range(self.num_layers):
            x = block_class(cfg, i)(cfg, attn, name=f"block_{i}")(x)
        x = nn.RMSNorm(dtype=self.dtype)(x)
        # Classify from the [CLS] token (f32 head, as in the LM's lm_head).
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x[:, 0].astype(jnp.float32))
