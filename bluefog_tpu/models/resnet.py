"""ResNet family (flax) — the reference's headline benchmark model.

The reference benchmarks torchvision's ResNet-50 (``examples/
pytorch_benchmark.py:57-70`` picks the model by name from torchvision); this
is a from-scratch flax implementation laid out for the MXU: NHWC layout,
``bfloat16`` compute / ``float32`` params by default, BN statistics in
float32, and 1x1/3x3 convs that XLA tiles straight onto the systolic array.

v1.5 variant (stride on the 3x3, as torchvision does): ResNet-50's
``(3, 4, 6, 3)`` bottleneck stacking.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "ResNet152"]

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale so blocks start as identity (standard
        # large-batch trick; torchvision's zero_init_residual).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = jnp.asarray(x, self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   conv=conv, norm=norm, act=self.act,
                                   strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return jnp.asarray(x, jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
