"""Decoder-only Transformer LM with pluggable attention.

The reference predates LLM workloads (SURVEY §5.7: no sequence parallelism
anywhere in its tree); this model exists so the framework's long-context
machinery (``bluefog_tpu.parallel.ring_attention`` /
``bluefog_tpu.parallel.ulysses``) has a first-class consumer: the
``attn_impl`` hook receives ``(q, k, v, causal)`` per head-batch and may be a
local attention, a ring attention over a mesh axis, or an all-to-all
(Ulysses) head-parallel attention.

MXU-friendly choices: bfloat16 activations, fused QKV projection, RMSNorm,
static shapes throughout.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["TransformerLM", "TransformerConfig", "local_attention"]


def local_attention(q, k, v, *, causal: bool = True):
    """Plain single-device attention: ``(B, S, H, D)`` inputs."""
    dt = q.dtype
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), s_k - s_q)
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(dt)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class TransformerConfig:
    def __init__(self, vocab_size=32000, num_layers=4, num_heads=8,
                 embed_dim=512, mlp_ratio=4, max_seq_len=2048,
                 dtype=jnp.bfloat16, remat=False, num_experts=0,
                 expert_capacity_factor=2.0, router_group_size=4096,
                 num_kv_heads=None, pos_encoding="learned",
                 rope_theta=10000.0, mlp="gelu"):
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        # Grouped-query attention (GQA; num_kv_heads=1 is MQA): fewer K/V
        # projection heads, repeated across query groups before attention,
        # so every attn_impl (local / flash / ring / Ulysses) sees uniform
        # (B, S, H, D) heads unchanged.  None = classic MHA.
        if num_kv_heads is not None and num_heads % num_kv_heads:
            raise ValueError(f"num_heads ({num_heads}) must be divisible "
                             f"by num_kv_heads ({num_kv_heads})")
        self.num_kv_heads = num_kv_heads
        # "learned" = absolute wpe table (default); "rope" = rotary applied
        # to q/k inside each block — positions flow in explicitly, so
        # sequence-parallel shards (ring/Ulysses) embed their own offsets
        # and the attention impl itself stays position-agnostic.
        if pos_encoding not in ("learned", "rope"):
            raise ValueError(f"pos_encoding {pos_encoding!r} not in "
                             "('learned', 'rope')")
        if pos_encoding == "rope" and (embed_dim // num_heads) % 2:
            raise ValueError(
                f"rope needs an even head dim; got embed_dim {embed_dim} / "
                f"num_heads {num_heads} = {embed_dim // num_heads}")
        self.pos_encoding = pos_encoding
        self.rope_theta = rope_theta
        if mlp not in ("gelu", "swiglu"):
            raise ValueError(f"mlp {mlp!r} not in ('gelu', 'swiglu')")
        if mlp == "swiglu" and num_experts:
            raise ValueError(
                "mlp='swiglu' with num_experts > 0 is contradictory: MoE "
                "blocks replace the MLP with GELU experts")
        self.mlp = mlp
        self.embed_dim = embed_dim
        self.mlp_ratio = mlp_ratio
        self.max_seq_len = max_seq_len
        self.dtype = dtype
        # jax.checkpoint per block: recompute activations in the backward
        # instead of keeping every layer's live — trades ~1/3 more FLOPs
        # for O(num_layers) less activation HBM, the standard long-context
        # training knob (pairs with the O(S)-memory flash attention).
        self.remat = remat
        # num_experts > 0 replaces each block's MLP with a switch-routed
        # mixture of experts (top-1, static capacity).  Expert weights are
        # stacked (E, ...) so ``parallel.tp_param_specs``-style expert
        # sharding (P("ep")) runs them expert-parallel under GSPMD.
        self.num_experts = num_experts
        self.expert_capacity_factor = expert_capacity_factor
        self.router_group_size = router_group_size


class SwitchMlp(nn.Module):
    """Top-1 routed mixture-of-experts MLP (Switch Transformer).

    Tokens route within fixed-size groups (``cfg.router_group_size``), so the
    one-hot dispatch tensors are O(T * group_size) — linear in sequence
    length — instead of the O(T^2) a single global group would cost.  Every
    shape is static under jit; expert weights are stacked ``(E, ...)`` so a
    ``P("ep")`` sharding on them runs the einsums expert-parallel with
    GSPMD-placed collectives — same layout-not-algorithm philosophy as
    ``parallel.tensor_parallel``.

    The standard load-balancing auxiliary loss (Switch eq. 4: E * sum_e
    f_e p_e per group) is sown as ``intermediates/moe_aux_loss`` — add
    ``aux_weight * sum(sown)`` to the training loss to keep the router from
    collapsing onto one expert."""
    cfg: Any

    @nn.compact
    def __call__(self, x):
        from bluefog_tpu.parallel.moe import switch_dispatch
        cfg = self.cfg
        B, S, d = x.shape
        E = cfg.num_experts
        hidden = cfg.mlp_ratio * d
        T = B * S
        g = min(getattr(cfg, "router_group_size", 4096), T)
        # Pad to a whole number of groups (never silently shrink g — tiny
        # groups disable the capacity guard and gut the balance statistic).
        G = -(-T // g)
        pad = G * g - T
        xt = x.reshape(T, d)
        if pad:
            xt = jnp.concatenate(
                [xt, jnp.zeros((pad, d), xt.dtype)], axis=0)
        xt = xt.reshape(G, g, d)
        capacity = max(1, int(cfg.expert_capacity_factor * g / E))
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          name="router")(xt.astype(jnp.float32))
        combine, dispatch = jax.vmap(
            lambda lg: switch_dispatch(lg, E, capacity))(logits)
        # Load balance (Switch eq. 4 per group): E * sum_e f_e p_e with f_e
        # the fraction of tokens ROUTED to e (pre-capacity argmax — the
        # clipped dispatch would saturate the gradient exactly when an
        # expert overflows).
        probs = jax.nn.softmax(logits, axis=-1)             # (G, g, E)
        routed = jax.nn.one_hot(jnp.argmax(probs, axis=-1), E,
                                dtype=probs.dtype)
        frac = routed.mean(axis=1)                          # (G, E)
        aux = (E * (frac * probs.mean(axis=1)).sum(-1)).mean()
        self.sow("intermediates", "moe_aux_loss", aux)
        # batch_axis keeps fan_in per expert (= d / hidden), not E*d.
        init = nn.initializers.lecun_normal(batch_axis=(0,))
        up = self.param("experts_up", init, (E, d, hidden))
        down = self.param("experts_down", init, (E, hidden, d))
        xe = jnp.einsum("gect,gtd->gecd", dispatch.astype(cfg.dtype),
                        xt.astype(cfg.dtype))
        ye = nn.gelu(jnp.einsum("gecd,edh->gech", xe,
                                up.astype(cfg.dtype)))
        ye = jnp.einsum("gech,ehd->gecd", ye, down.astype(cfg.dtype))
        y = jnp.einsum("gtec,gecd->gtd", combine.astype(cfg.dtype), ye)
        return y.reshape(G * g, d)[:T].reshape(B, S, d)


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotary position embedding on ``(B, S, H, D)`` q or k.

    Pairs dimension ``i`` with ``i + D/2`` (the standard half-split layout)
    and rotates by ``pos * theta^(-2i/D)``; angles computed in f32, result
    cast back to the input dtype."""
    d2 = x.shape[-1] // 2
    freq = theta ** (-jnp.arange(d2, dtype=jnp.float32) / d2)
    ang = positions[..., None].astype(jnp.float32) * freq  # (B, S, d2)
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :d2].astype(jnp.float32), x[..., d2:].astype(jnp.float32)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], -1).astype(x.dtype)


class Block(nn.Module):
    cfg: Any
    attn_impl: Callable

    @nn.compact
    def __call__(self, x, positions=None):
        cfg = self.cfg
        h = cfg.num_heads
        d = cfg.embed_dim // h
        kv_h = cfg.num_kv_heads or h
        rope = getattr(cfg, "pos_encoding", "learned") == "rope"
        y = nn.RMSNorm(dtype=cfg.dtype)(x)
        B, S = y.shape[0], y.shape[1]
        if kv_h == h:
            qkv = nn.Dense(3 * cfg.embed_dim, use_bias=False,
                           dtype=cfg.dtype, name="qkv")(y)
            # Head-interleaved fused layout [q_h0 k_h0 v_h0 | q_h1 ...]: a
            # pure relabeling of kernel columns that keeps tensor-parallel
            # shard boundaries (tp_param_specs' column split) aligned to
            # heads, so GSPMD runs attention head-parallel with one psum
            # per block instead of per-activation resharding.
            qkv = qkv.reshape(B, S, h, 3, d)
            q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
            if rope:
                q = apply_rope(q, positions, cfg.rope_theta)
                k = apply_rope(k, positions, cfg.rope_theta)
        else:
            # GQA: h query heads, kv_h shared K/V heads (same interleaved
            # column layout per projection; head-aligned TP only up to
            # kv_h ways — beyond that GSPMD re-gathers K/V per block,
            # acceptable since the kv kernel is the small one).
            q = nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                         name="q")(y).reshape(B, S, h, d)
            kv = nn.Dense(2 * kv_h * d, use_bias=False, dtype=cfg.dtype,
                          name="kv")(y).reshape(B, S, kv_h, 2, d)
            rep = h // kv_h
            k1 = kv[..., 0, :]
            if rope:
                # rotate the kv_h shared heads ONCE, before fan-out to h
                q = apply_rope(q, positions, cfg.rope_theta)
                k1 = apply_rope(k1, positions, cfg.rope_theta)
            k = jnp.repeat(k1, rep, axis=2)
            v = jnp.repeat(kv[..., 1, :], rep, axis=2)
        attn = self.attn_impl(q, k, v, causal=True)
        attn = attn.reshape(B, S, cfg.embed_dim)
        x = x + nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                         name="proj")(attn)
        y = nn.RMSNorm(dtype=cfg.dtype)(x)
        if getattr(cfg, "num_experts", 0) > 0:
            x = x + SwitchMlp(cfg, name="moe")(y)
        elif getattr(cfg, "mlp", "gelu") == "swiglu":
            hidden = cfg.mlp_ratio * cfg.embed_dim
            gate = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype,
                            name="gate")(y)
            up = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype,
                          name="up")(y)
            x = x + nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                             name="down")(nn.silu(gate) * up)
        else:
            y = nn.Dense(cfg.mlp_ratio * cfg.embed_dim, use_bias=False,
                         dtype=cfg.dtype, name="up")(y)
            y = nn.gelu(y)
            x = x + nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                             name="down")(y)
        return x


class TransformerLM(nn.Module):
    cfg: Any
    attn_impl: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens, train: bool = True, positions=None,
                 return_hidden: bool = False):
        """``positions``: optional (B, S) global position ids — required when
        the sequence axis is sharded (each shard must embed its own offset).
        ``return_hidden``: skip the lm-head and return the final normalized
        activations (B, S, E) — pair with
        ``ops.chunked_loss.chunked_softmax_cross_entropy`` so very long
        sequences never materialize the (S, vocab) logits."""
        cfg = self.cfg
        attn = self.attn_impl or local_attention
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     dtype=cfg.dtype, name="wte")(tokens)
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :]
        rope = getattr(cfg, "pos_encoding", "learned") == "rope"
        if not rope:
            pos = nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                           dtype=cfg.dtype, name="wpe")(positions)
            x = x + pos
        positions = jnp.broadcast_to(positions,
                                     (tokens.shape[0], tokens.shape[1]))
        block_cls = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.num_layers):
            blk = block_cls(cfg, attn, name=f"block_{i}")
            x = blk(x, positions) if rope else blk(x)
        x = nn.RMSNorm(dtype=cfg.dtype)(x)
        head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")
        if return_hidden:
            head(x[:, :1])  # materialize the lm_head param without S x V
            return x
        return head(x)
