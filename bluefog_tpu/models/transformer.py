"""Decoder-only Transformer LM with pluggable attention.

The reference predates LLM workloads (SURVEY §5.7: no sequence parallelism
anywhere in its tree); this model exists so the framework's long-context
machinery (``bluefog_tpu.parallel.ring_attention`` /
``bluefog_tpu.parallel.ulysses``) has a first-class consumer: the
``attn_impl`` hook receives ``(q, k, v, causal)`` per head-batch and may be a
local attention, a ring attention over a mesh axis, or an all-to-all
(Ulysses) head-parallel attention.

MXU-friendly choices: bfloat16 activations, fused QKV projection, RMSNorm,
static shapes throughout.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["TransformerLM", "TransformerConfig", "local_attention",
           "init_cache", "generate"]


def local_attention(q, k, v, *, causal: bool = True):
    """Plain single-device attention: ``(B, S, H, D)`` inputs."""
    dt = q.dtype
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), s_k - s_q)
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = nn.softmax(logits.astype(jnp.float32), axis=-1).astype(dt)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class TransformerConfig:
    def __init__(self, vocab_size=32000, num_layers=4, num_heads=8,
                 embed_dim=512, mlp_ratio=4, max_seq_len=2048,
                 dtype=jnp.bfloat16, remat=False, remat_policy="full",
                 causal=True, num_experts=0,
                 expert_capacity_factor=2.0, router_group_size=4096,
                 num_kv_heads=None, pos_encoding="learned",
                 rope_theta=10000.0, mlp="gelu"):
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        # Grouped-query attention (GQA; num_kv_heads=1 is MQA): fewer K/V
        # projection heads, repeated across query groups before attention,
        # so every attn_impl (local / flash / ring / Ulysses) sees uniform
        # (B, S, H, D) heads unchanged.  None = classic MHA.
        if num_kv_heads is not None and num_heads % num_kv_heads:
            raise ValueError(f"num_heads ({num_heads}) must be divisible "
                             f"by num_kv_heads ({num_kv_heads})")
        self.num_kv_heads = num_kv_heads
        # "learned" = absolute wpe table (default); "rope" = rotary applied
        # to q/k inside each block — positions flow in explicitly, so
        # sequence-parallel shards (ring/Ulysses) embed their own offsets
        # and the attention impl itself stays position-agnostic.
        if pos_encoding not in ("learned", "rope"):
            raise ValueError(f"pos_encoding {pos_encoding!r} not in "
                             "('learned', 'rope')")
        if pos_encoding == "rope" and (embed_dim // num_heads) % 2:
            raise ValueError(
                f"rope needs an even head dim; got embed_dim {embed_dim} / "
                f"num_heads {num_heads} = {embed_dim // num_heads}")
        self.pos_encoding = pos_encoding
        self.rope_theta = rope_theta
        if mlp not in ("gelu", "swiglu"):
            raise ValueError(f"mlp {mlp!r} not in ('gelu', 'swiglu')")
        if mlp == "swiglu" and num_experts:
            raise ValueError(
                "mlp='swiglu' with num_experts > 0 is contradictory: MoE "
                "blocks replace the MLP with GELU experts")
        self.mlp = mlp
        self.embed_dim = embed_dim
        self.mlp_ratio = mlp_ratio
        self.max_seq_len = max_seq_len
        self.dtype = dtype
        # jax.checkpoint per block: recompute activations in the backward
        # instead of keeping every layer's live — trades ~1/3 more FLOPs
        # for O(num_layers) less activation HBM, the standard long-context
        # training knob (pairs with the O(S)-memory flash attention).
        self.remat = remat
        if remat_policy not in ("full", "dots") and not (
                isinstance(remat_policy, str)
                and remat_policy.startswith("dots:")):
            raise ValueError(f"remat_policy {remat_policy!r} not in "
                             "('full', 'dots', 'dots:<K>')")
        if isinstance(remat_policy, str) and remat_policy.startswith("dots:"):
            # Mixed policy: the first K blocks keep their dot_general
            # outputs resident ('dots' — less backward recompute), the
            # remaining blocks use full per-block remat.  The HBM knob for
            # models where all-dots exceeds memory but full remat leaves
            # MFU on the table (the 1.3B headline: dots is +13% where it
            # fits; K dials resident-activation memory continuously).
            try:
                k = int(remat_policy.split(":", 1)[1])
            except ValueError:
                raise ValueError(
                    f"malformed {remat_policy!r}: use 'dots:<int>'"
                ) from None
            if k < 0:
                raise ValueError(f"remat_policy dots:K needs K >= 0, got {k}")
        self.remat_policy = remat_policy
        # causal=False gives BIDIRECTIONAL attention (encoder mode — the
        # ViT uses it); the KV-cache decode path requires causal=True.
        self.causal = causal
        # num_experts > 0 replaces each block's MLP with a switch-routed
        # mixture of experts (top-1, static capacity).  Expert weights are
        # stacked (E, ...) so ``parallel.tp_param_specs``-style expert
        # sharding (P("ep")) runs them expert-parallel under GSPMD.
        self.num_experts = num_experts
        self.expert_capacity_factor = expert_capacity_factor
        self.router_group_size = router_group_size


class SwitchMlp(nn.Module):
    """Top-1 routed mixture-of-experts MLP (Switch Transformer).

    Tokens route within fixed-size groups (``cfg.router_group_size``), so the
    one-hot dispatch tensors are O(T * group_size) — linear in sequence
    length — instead of the O(T^2) a single global group would cost.  Every
    shape is static under jit; expert weights are stacked ``(E, ...)`` so a
    ``P("ep")`` sharding on them runs the einsums expert-parallel with
    GSPMD-placed collectives — same layout-not-algorithm philosophy as
    ``parallel.tensor_parallel``.

    The standard load-balancing auxiliary loss (Switch eq. 4: E * sum_e
    f_e p_e per group) is sown as ``intermediates/moe_aux_loss`` — add
    ``aux_weight * sum(sown)`` to the training loss to keep the router from
    collapsing onto one expert."""
    cfg: Any

    @nn.compact
    def __call__(self, x):
        from bluefog_tpu.parallel.moe import (load_balance_loss,
                                              switch_dispatch)
        cfg = self.cfg
        B, S, d = x.shape
        E = cfg.num_experts
        hidden = cfg.mlp_ratio * d
        T = B * S
        g = min(getattr(cfg, "router_group_size", 4096), T)
        # Pad to a whole number of groups (never silently shrink g — tiny
        # groups disable the capacity guard and gut the balance statistic).
        G = -(-T // g)
        pad = G * g - T
        xt = x.reshape(T, d)
        if pad:
            xt = jnp.concatenate(
                [xt, jnp.zeros((pad, d), xt.dtype)], axis=0)
        xt = xt.reshape(G, g, d)
        capacity = max(1, int(cfg.expert_capacity_factor * g / E))
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          name="router")(xt.astype(jnp.float32))
        # Padding tokens route nowhere: without the mask their all-zero
        # logit rows argmax to expert 0, eat its capacity in the last
        # group, and skew the balance statistic toward it.
        valid = (jnp.arange(G * g) < T).astype(jnp.float32).reshape(G, g)
        combine, dispatch = jax.vmap(
            lambda lg, v: switch_dispatch(lg, E, capacity, v))(logits,
                                                               valid)
        # Load balance (Switch eq. 4, per routing group, mean over groups);
        # single-sourced in parallel.moe.load_balance_loss.
        aux = jax.vmap(load_balance_loss)(logits, valid).mean()
        self.sow("intermediates", "moe_aux_loss", aux)
        # batch_axis keeps fan_in per expert (= d / hidden), not E*d.
        init = nn.initializers.lecun_normal(batch_axis=(0,))
        up = self.param("experts_up", init, (E, d, hidden))
        down = self.param("experts_down", init, (E, hidden, d))
        xe = jnp.einsum("gect,gtd->gecd", dispatch.astype(cfg.dtype),
                        xt.astype(cfg.dtype))
        ye = nn.gelu(jnp.einsum("gecd,edh->gech", xe,
                                up.astype(cfg.dtype)))
        ye = jnp.einsum("gech,ehd->gecd", ye, down.astype(cfg.dtype))
        y = jnp.einsum("gtec,gecd->gtd", combine.astype(cfg.dtype), ye)
        return y.reshape(G * g, d)[:T].reshape(B, S, d)


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotary position embedding on ``(B, S, H, D)`` q or k.

    Pairs dimension ``i`` with ``i + D/2`` (the standard half-split layout)
    and rotates by ``pos * theta^(-2i/D)``; angles computed in f32, result
    cast back to the input dtype."""
    d2 = x.shape[-1] // 2
    freq = theta ** (-jnp.arange(d2, dtype=jnp.float32) / d2)
    ang = positions[..., None].astype(jnp.float32) * freq  # (B, S, d2)
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :d2].astype(jnp.float32), x[..., d2:].astype(jnp.float32)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], -1).astype(x.dtype)


def block_class(cfg, layer_idx: int = None):
    """The (possibly remat-wrapped) Block class for a config — shared by
    ``TransformerLM`` and ``models.vit.ViT`` so ``remat_policy`` behaves
    identically in both.  ``layer_idx`` selects the per-layer class under
    the mixed ``"dots:<K>"`` policy (None = single-policy configs)."""
    if not cfg.remat:
        return Block
    policy = getattr(cfg, "remat_policy", "full")
    if isinstance(policy, str) and policy.startswith("dots:"):
        k = int(policy.split(":", 1)[1])
        if layer_idx is None:
            raise ValueError(
                "remat_policy='dots:<K>' is per-layer — call "
                "block_class(cfg, layer_idx=i)")
        policy = "dots" if layer_idx < k else "full"
    if policy == "dots":
        # Save every dot_general output, recompute only non-dot ops in
        # the backward: less recompute than full remat at the cost of
        # keeping dot activations resident.  NOTE: with dense
        # local_attention the (B,H,S,S) score/value einsums ARE dots
        # and stay live — at long S use flash attention (a pallas_call,
        # not a dot_general: recomputed, O(S) memory) or "full".
        return nn.remat(Block, policy=jax.checkpoint_policies.checkpoint_dots)
    return nn.remat(Block)


class Block(nn.Module):
    cfg: Any
    attn_impl: Callable

    @nn.compact
    def __call__(self, x, positions=None, cache=None):
        """Training/prefill path when ``cache is None``; with ``cache =
        (k_cache, v_cache)`` (shapes ``(B, L, kv_h, d)``) the input is ONE
        new token per sequence (S == 1) written at position ``positions``
        and attended against the cache — returns ``(x, new_cache)``.  The
        cache stores the kv_h *shared* heads, so GQA shrinks it by
        ``h / kv_h`` (the reason GQA exists)."""
        cfg = self.cfg
        h = cfg.num_heads
        d = cfg.embed_dim // h
        kv_h = cfg.num_kv_heads or h
        rope = getattr(cfg, "pos_encoding", "learned") == "rope"
        if rope and positions is None and cache is None:
            # standalone Block use (e.g. pipeline stages): local positions
            positions = jnp.arange(x.shape[1])[None, :]
        y = nn.RMSNorm(dtype=cfg.dtype)(x)
        B, S = y.shape[0], y.shape[1]
        if kv_h == h:
            qkv = nn.Dense(3 * cfg.embed_dim, use_bias=False,
                           dtype=cfg.dtype, name="qkv")(y)
            # Head-interleaved fused layout [q_h0 k_h0 v_h0 | q_h1 ...]: a
            # pure relabeling of kernel columns that keeps tensor-parallel
            # shard boundaries (tp_param_specs' column split) aligned to
            # heads, so GSPMD runs attention head-parallel with one psum
            # per block instead of per-activation resharding.
            qkv = qkv.reshape(B, S, h, 3, d)
            q, k1, v1 = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        else:
            # GQA: h query heads, kv_h shared K/V heads (same interleaved
            # column layout per projection; head-aligned TP only up to
            # kv_h ways — beyond that GSPMD re-gathers K/V per block,
            # acceptable since the kv kernel is the small one).
            q = nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                         name="q")(y).reshape(B, S, h, d)
            kv = nn.Dense(2 * kv_h * d, use_bias=False, dtype=cfg.dtype,
                          name="kv")(y).reshape(B, S, kv_h, 2, d)
            k1, v1 = kv[..., 0, :], kv[..., 1, :]
        if rope:
            # rotate the kv_h shared heads ONCE, before any fan-out to h
            q = apply_rope(q, positions, cfg.rope_theta)
            k1 = apply_rope(k1, positions, cfg.rope_theta)
        rep = h // kv_h
        if cache is None:
            if (self.is_mutable_collection("kv_cache")
                    and not self.is_initializing()):
                # prefill: expose the per-position shared-head K/V so
                # ``generate`` can fill its decode cache in ONE forward.
                # Gated out of init(), which would otherwise bake a stale
                # entry into the variables users carry around.
                self.sow("kv_cache", "kv_entries", (k1, v1))
            k = jnp.repeat(k1, rep, axis=2) if rep > 1 else k1
            v = jnp.repeat(v1, rep, axis=2) if rep > 1 else v1
            attn = self.attn_impl(
                q, k, v, causal=getattr(self.cfg, "causal", True))
        else:
            ck, cv = cache
            idx = positions[0, 0]  # decode positions are batch-uniform
            ck = jax.lax.dynamic_update_slice(
                ck, k1.astype(ck.dtype), (0, idx, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, v1.astype(cv.dtype), (0, idx, 0, 0))
            cache = (ck, cv)
            # grouped attention of the single query over the cache — never
            # materializes h-head K/V
            L = ck.shape[1]
            qg = q.reshape(B, S, kv_h, rep, d)
            logits = jnp.einsum("bqgrd,blgd->bgrql", qg, ck) / np.sqrt(d)
            mask = (jnp.arange(L) <= idx)[None, None, None, None, :]
            logits = jnp.where(mask, logits.astype(jnp.float32),
                               jnp.finfo(jnp.float32).min)
            probs = nn.softmax(logits, axis=-1).astype(cfg.dtype)
            attn = jnp.einsum("bgrql,blgd->bqgrd", probs, cv)
        attn = attn.reshape(B, S, cfg.embed_dim)
        x = x + nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                         name="proj")(attn)
        y = nn.RMSNorm(dtype=cfg.dtype)(x)
        if getattr(cfg, "num_experts", 0) > 0:
            x = x + SwitchMlp(cfg, name="moe")(y)
        elif getattr(cfg, "mlp", "gelu") == "swiglu":
            hidden = cfg.mlp_ratio * cfg.embed_dim
            gate = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype,
                            name="gate")(y)
            up = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype,
                          name="up")(y)
            x = x + nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                             name="down")(nn.silu(gate) * up)
        else:
            y = nn.Dense(cfg.mlp_ratio * cfg.embed_dim, use_bias=False,
                         dtype=cfg.dtype, name="up")(y)
            y = nn.gelu(y)
            x = x + nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                             name="down")(y)
        return x if cache is None else (x, cache)


class TransformerLM(nn.Module):
    cfg: Any
    attn_impl: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens, train: bool = True, positions=None,
                 return_hidden: bool = False, cache=None):
        """``positions``: optional (B, S) global position ids — required when
        the sequence axis is sharded (each shard must embed its own offset).
        ``return_hidden``: skip the lm-head and return the final normalized
        activations (B, S, E) — pair with
        ``ops.chunked_loss.chunked_softmax_cross_entropy`` so very long
        sequences never materialize the (S, vocab) logits.
        ``cache``: list of per-block ``(k, v)`` caches (``init_cache``) for
        single-token incremental decoding — tokens must be (B, 1) at
        position ``positions``; returns ``(logits, new_cache)``."""
        cfg = self.cfg
        attn = self.attn_impl or local_attention
        if cache is not None:
            if getattr(cfg, "num_experts", 0) > 0:
                raise NotImplementedError(
                    "KV-cache decoding with MoE blocks is not supported")
            if not getattr(cfg, "causal", True):
                raise ValueError(
                    "KV-cache decoding requires causal=True: the decode "
                    "branch masks by cache index (causal by construction), "
                    "which would diverge from a bidirectional training "
                    "forward")
            if tokens.shape[1] != 1:
                raise ValueError(
                    f"cache decoding takes ONE token per step; got "
                    f"tokens of shape {tokens.shape} (prefill a prompt "
                    f"with a normal forward — see generate())")
            if positions is None:
                raise ValueError(
                    "cache decoding requires explicit positions (the "
                    "cache write index); defaulting to 0 would overwrite "
                    "slot 0 every step")
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     dtype=cfg.dtype, name="wte")(tokens)
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :]
        rope = getattr(cfg, "pos_encoding", "learned") == "rope"
        if not rope:
            pos = nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                           dtype=cfg.dtype, name="wpe")(positions)
            x = x + pos
        positions = jnp.broadcast_to(positions,
                                     (tokens.shape[0], tokens.shape[1]))
        new_cache = []
        for i in range(cfg.num_layers):
            block_cls = Block if cache is not None else block_class(cfg, i)
            blk = block_cls(cfg, attn, name=f"block_{i}")
            if cache is not None:
                x, blk_cache = blk(x, positions, cache[i])
                new_cache.append(blk_cache)
            elif rope:
                x = blk(x, positions)
            else:
                x = blk(x)
        x = nn.RMSNorm(dtype=cfg.dtype)(x)
        head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")
        if return_hidden:
            head(x[:, :1])  # materialize the lm_head param without S x V
            return x
        if cache is not None:
            return head(x), new_cache
        return head(x)


def init_cache(cfg, batch: int, max_len: int):
    """Per-block ``(k, v)`` KV caches for incremental decoding: shapes
    ``(batch, max_len, kv_heads, head_dim)`` — kv_heads, not num_heads, so
    GQA/MQA caches are ``num_heads / num_kv_heads`` times smaller."""
    h = cfg.num_heads
    d = cfg.embed_dim // h
    kv_h = cfg.num_kv_heads or h
    z = jnp.zeros((batch, max_len, kv_h, d), cfg.dtype)
    return [(z, z) for _ in range(cfg.num_layers)]


def generate(model, variables, prompt, max_new_tokens: int, *,
             temperature: float = 0.0, rng=None):
    """Autoregressive decoding with the KV cache.

    ``prompt``: (B, P) int tokens.  Returns (B, max_new_tokens).
    ``temperature == 0`` is greedy; otherwise pass ``rng`` for sampling.
    Prefill is ONE batched forward (the per-block shared-head K/V are sown
    into a ``kv_cache`` collection and copied into the decode cache), then
    new tokens stream through a single fused ``lax.scan`` of one-token
    decode steps.  Decode logits match the training forward's to numerical
    tolerance (different contraction order; tested at 1e-4 in f32).
    """
    cfg = model.cfg
    B, P = prompt.shape
    if max_new_tokens <= 0:
        raise ValueError(f"max_new_tokens must be >= 1; got {max_new_tokens}")
    total = P + max_new_tokens
    if getattr(cfg, "pos_encoding", "learned") == "learned" \
            and total > cfg.max_seq_len:
        raise ValueError(f"prompt + max_new_tokens = {total} exceeds "
                         f"max_seq_len {cfg.max_seq_len}")
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) needs rng")
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def pick(logits, key):
        if temperature > 0:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        return nxt.astype(prompt.dtype), key

    # Prefill: one forward over the whole prompt; blocks sow (k1, v1).
    # (drop any stale kv_cache collection an old init may have stored)
    variables = {k: v for k, v in variables.items() if k != "kv_cache"}
    logits, sown = model.apply(
        variables, prompt, positions=jnp.arange(P)[None, :],
        mutable=["kv_cache"])
    cache = []
    for i, (ck, cv) in enumerate(init_cache(cfg, B, total)):
        (k1, v1), = sown["kv_cache"][f"block_{i}"]["kv_entries"]
        cache.append((jax.lax.dynamic_update_slice(
                          ck, k1.astype(ck.dtype), (0, 0, 0, 0)),
                      jax.lax.dynamic_update_slice(
                          cv, v1.astype(cv.dtype), (0, 0, 0, 0))))
    first, rng = pick(logits[:, -1, :], rng)

    def step(carry, t):
        cache, prev, key = carry
        logits, cache = model.apply(
            variables, prev[:, None],
            positions=jnp.broadcast_to(t, (B, 1)), cache=cache)
        nxt, key = pick(logits[:, 0, :], key)
        return (cache, nxt, key), nxt

    if max_new_tokens == 1:
        return first[:, None]
    (_, _, _), outs = jax.lax.scan(
        step, (cache, first, rng), jnp.arange(P, total - 1))
    return jnp.concatenate([first[:, None], outs.swapaxes(0, 1)], axis=1)
