"""VGG family (flax) — the reference's second benchmark CNN class.

The reference benchmarks any torchvision model by name, VGG-16 being the
standard bandwidth-heavy second datapoint next to ResNet-50
(``examples/pytorch_benchmark.py:57-70``).  TPU-idiomatic choices match the
ResNet implementation: NHWC layout, bfloat16 compute / float32 params, and
plain 3x3 convs that XLA tiles straight onto the MXU.  BatchNorm is omitted
(classic VGG predates it; torchvision's default ``vgg16`` likewise) — each
conv carries a bias instead.  torchvision's classifier ``Dropout(0.5)``
layers are ALSO omitted (they would need a dropout rng threaded through
every benchmark/train call for a regularizer that does not change the
throughput-parity question); the ``train`` flag is accepted for API
symmetry with the ResNet family but currently has no effect.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["VGG", "VGG11", "VGG16", "VGG19"]

# Numbers = conv output channels, "M" = 2x2 max pool (torchvision cfgs).
_CFGS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence[Any]
    num_classes: int = 1000
    hidden: int = 4096
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding=1, dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def VGG11(**kw) -> VGG:
    return VGG(_CFGS[11], **kw)


def VGG16(**kw) -> VGG:
    return VGG(_CFGS[16], **kw)


def VGG19(**kw) -> VGG:
    return VGG(_CFGS[19], **kw)
