"""Model zoo (flax): benchmark and example workloads.

Covers the reference's benchmark/example model needs
(``examples/pytorch_benchmark.py`` uses torchvision resnet/vgg etc.;
``examples/pytorch_mnist.py`` LeNet-ish CNN; optimization examples use
linear/logistic models) with TPU-idiomatic flax implementations, plus a
Transformer LM as the long-context workload consumer.
"""

from bluefog_tpu.models.resnet import (  # noqa: F401
    ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152,
)
from bluefog_tpu.models.simple import (  # noqa: F401
    LeNet5, MLP, LogisticRegression, LinearModel,
)
from bluefog_tpu.models.transformer import (  # noqa: F401
    TransformerLM, TransformerConfig, local_attention,
)
from bluefog_tpu.models.vgg import (  # noqa: F401
    VGG, VGG11, VGG16, VGG19,
)
from bluefog_tpu.models.vit import ViT  # noqa: F401
