"""Zero-copy device→wire window puts (``BLUEFOG_TPU_WIN_XLA``).

Python face of ``native/src/xlacall.cc``: a window put/accumulate whose
remote edges all ride the native transport is compiled once into a PUT
PLAN (per-edge peer endpoint, wire op, weight, row offset, codec), and
each dispatch hands the XLA buffer pointer straight to
``bf_xla_plan_run`` — the rows are encoded into the ``bf_wintx_*``
per-peer arenas IN C, with no ``jax.device_get``, no per-edge numpy
temporary, no ``tobytes`` and no per-edge Python loop.  On the CPU
backend (tier-1 and bench environment) the XLA buffer *is* host memory,
so the zero-copy is real and measurable today; the TPU lowering reuses
the same plan/FFI signature behind the capability check below.

Two dispatch routes share the one native executor:

* **eager** (the window-op hot path): ``jax.Array.unsafe_buffer_pointer``
  → one ctypes call into ``bf_xla_plan_run`` — microseconds of host work
  per put, independent of row size;
* **in-program** (``bf_xla_win_put``): the same plan lowered to an XLA
  FFI custom call (registered through ``jax.ffi`` /
  ``jax.extend.ffi``), so a compiled step can issue its puts while XLA
  is still executing the rest of the program — :func:`xla_put_program`.

Arming (``BLUEFOG_TPU_WIN_XLA``, default on): requires the jax FFI
module (``_compat.jax_ffi``), a current native core carrying the
``bf_xla_*`` symbols, and host-addressable device buffers (CPU backend).
Anything missing auto-disarms with ONE logged warning and the PR-9 path
— kept fully intact — serves every put (``=0`` pins it unconditionally:
the bitwise equivalence oracle, same contract PR 9 used for
``BLUEFOG_TPU_WIN_NATIVE``).

This module also owns the ``bf_win_host_copy_bytes_total{path}``
accounting helpers: every host-side staging copy on the put/drain path
(``device_get``, per-edge temp, enqueue copy, commit re-upload) counts
its bytes here, verified by pointer identity where the runtime allows —
the oracle proving which copies the FFI path actually eliminated.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu import native
from bluefog_tpu.utils import config

__all__ = ["armed", "disarm_reason", "keep_device_ok", "prepare_put",
           "run_group", "host_view", "commit_to_jax", "invalidate",
           "count_host_copy", "xla_put_program", "info"]

# Wire flag/op mirrors (ops/transport.py is the single source of truth).
_OP_ACCUMULATE = 2

_F32 = np.dtype(np.float32)

# Hot-path caches: the native handle (native.lib() takes a lock per call)
# and the jax.Array type (resolved once — jax is already imported by the
# window layer before any put can reach here).
_lib_cache = [None]


def _lib():
    lib = _lib_cache[0]
    if lib is None:
        lib = _lib_cache[0] = native.lib()
    return lib


def count_host_copy(nbytes, path: str) -> None:
    """One host-side staging copy of ``nbytes`` on the put/drain path."""
    from bluefog_tpu.utils import telemetry
    if nbytes and telemetry.enabled():
        telemetry.inc("bf_win_host_copy_bytes_total", float(nbytes),
                      path=path)


# ---------------------------------------------------------------------------
# Arming
# ---------------------------------------------------------------------------

_lock = threading.RLock()
# (config instance) -> (armed, reason); re-evaluated when config reloads.
_armed_cache: Tuple[object, bool, Optional[str]] = (None, False, None)
_warned = False


def _evaluate() -> Tuple[bool, Optional[str]]:
    cfg = config.get()
    if not cfg.win_xla:
        return False, "BLUEFOG_TPU_WIN_XLA=0"
    from bluefog_tpu import _compat
    if _compat.jax_ffi() is None:
        return False, ("this jax release has no jax.ffi / jax.extend.ffi "
                       "module")
    if not native.has_win_xla():
        return False, ("native core lacks the bf_xla_plan symbols "
                       "(stale or old .so — run `make -C "
                       "bluefog_tpu/native`)")
    import jax
    if jax.default_backend() != "cpu":
        return False, (f"backend {jax.default_backend()!r}: device buffers "
                       "are not host-addressable (TPU lowering pending)")
    return True, None


def armed() -> bool:
    """Whether the zero-copy put path is armed (cached per config load;
    auto-disarm logs one warning naming the missing capability)."""
    global _armed_cache, _warned
    cfg = config.get()
    with _lock:
        if _armed_cache[0] is cfg:
            return _armed_cache[1]
        ok, reason = _evaluate()
        _armed_cache = (cfg, ok, reason)
        if not ok and cfg.win_xla and not _warned:
            _warned = True
            from bluefog_tpu.utils.logging import get_logger
            get_logger().warning(
                "window XLA put path disarmed: %s — every put keeps the "
                "host-staged native path (BLUEFOG_TPU_WIN_XLA=0 silences "
                "this)", reason)
        return ok


def disarm_reason() -> Optional[str]:
    armed()
    return _armed_cache[2]


def info() -> dict:
    """Diagnostic summary (``bf.win_xla_info`` surfaces this)."""
    return {
        "armed": armed(),
        "reason": disarm_reason(),
        "handler": native.has_xla_handler(),
        "plans": len(_plan_cache),
    }


_jax_array_type = [None]


def keep_device_ok(tensor, win) -> bool:
    """Should this put keep ``tensor`` on device (skip the caller-thread
    ``_to_numpy``)?  True only when the FFI put path could serve it: a
    committed f32 ``jax.Array`` on an f32 window, with a live native
    transport to lower onto."""
    jat = _jax_array_type[0]
    if jat is None:
        import jax
        jat = _jax_array_type[0] = jax.Array
    if not isinstance(tensor, jat) or win.dtype != _F32:
        return False
    from bluefog_tpu.ops import window as W
    d = W._store.distrib
    if d is None or not armed():
        return False
    t = getattr(d, "transport", None)
    if t is None or not getattr(t, "native_path", False) \
            or not getattr(t, "_tx", None):
        return False
    # Multi-host sharded arrays have no single buffer pointer (and their
    # host materialization needs the shard-assembly path): host-staged.
    if not getattr(tensor, "is_fully_addressable", True):
        return False
    return tensor.dtype == _F32


# ---------------------------------------------------------------------------
# Put plans
# ---------------------------------------------------------------------------

class PutPlan:
    """One compiled put dispatch: either a single native plan covering
    every remote edge (``groups == [(plan_id, edges)]``) or one plan per
    edge (the ``require_mutex`` form, dispatched inside each edge's
    distributed-mutex hold)."""

    __slots__ = ("name", "op", "comp", "codec", "elems", "groups",
                 "proc_bytes", "total_bytes", "n_edges", "dispatch_lock",
                 "p_set")

    def __init__(self, name, op, comp, elems, groups, edge_bytes,
                 edge_procs):
        # Serializes set_p + run per plan: two concurrent puts sharing
        # one cached plan must not interleave another put's associated-P
        # refresh between their own refresh and dispatch (push-sum mass
        # would be mis-attributed) — the legacy per-edge loop reads p
        # inside its own send, so it has no such window.
        self.dispatch_lock = threading.Lock()
        self.name = name
        self.op = op
        self.comp = comp
        self.codec = _codec_id(comp, op)
        # Whether the native edges currently carry nonzero associated-P
        # masses: a put after turn_off_win_ops_with_associated_p() must
        # re-zero them or the cached plan would ship stale P on the wire
        # (the host-path oracle ships 0.0).
        self.p_set = False
        self.elems = elems
        self.groups = groups          # [(plan_id, [((src, dst), w), ...])]
        self.n_edges = len(edge_bytes)
        # Wire bytes aggregated per peer process at BUILD time, so the
        # per-dispatch telemetry is one counter bump per proc instead of
        # one per edge (the record path is on the put hot loop).
        self.proc_bytes: Dict[int, float] = {}
        for proc, nbytes in zip(edge_procs, edge_bytes):
            self.proc_bytes[proc] = self.proc_bytes.get(proc, 0.0) + nbytes
        self.total_bytes = float(sum(edge_bytes))


# (id(distrib), name, op, comp, per_edge, edges_tuple) -> PutPlan
_plan_cache: Dict[tuple, PutPlan] = {}
_PLAN_CACHE_MAX = 256


def _wire_bytes(comp: str, op: int, elems: int) -> int:
    """Wire payload bytes of one encoded row — the ONE rule this path and
    the telemetry accounting share (mirrors ``_send_to_proc``'s codec
    choice: sparse is accumulate-only, puts stay exact)."""
    if comp.startswith("sparse") and (op & 0x8F) == _OP_ACCUMULATE:
        k = max(1, int(np.ceil(config.parse_sparse_frac(comp) * elems)))
        k = min(k, elems)
        return 4 + 8 * k
    if comp == "bf16":
        return elems * 2
    return elems * 4


def _codec_id(comp: str, op: int) -> int:
    if comp.startswith("sparse") and (op & 0x8F) == _OP_ACCUMULATE:
        return 2
    if comp == "bf16":
        return 1
    return 0


def prepare_put(d, win, name: str, op: int,
                remote_edges: Sequence[Tuple[Tuple[int, int], float]],
                per_edge: bool) -> Optional[PutPlan]:
    """Resolve (and cache) the put plan for one dispatch, or None when the
    path cannot serve it (plan build failure → caller falls back to the
    host-staged path for this put)."""
    if not remote_edges:
        return None
    comp = config.get().win_compression
    key = (id(d), name, op, comp, bool(per_edge), tuple(remote_edges))
    with _lock:
        plan = _plan_cache.get(key)
        if plan is not None:
            return plan
    lib = native.lib()
    if lib is None or not native.has_win_xla():
        return None
    elems = int(np.prod(win.shape, dtype=np.int64))
    if elems <= 0 or len(name.encode()) >= 128:
        return None
    codec = _codec_id(comp, op)
    frac = (config.parse_sparse_frac(comp) if codec == 2 else 1.0)
    groups: List[tuple] = []
    edge_list = list(remote_edges)
    edge_groups = ([[e] for e in edge_list] if per_edge else [edge_list])
    for grp in edge_groups:
        pid = lib.bf_xla_plan_new(name.encode(), elems, len(grp), codec,
                                  frac)
        if pid <= 0:
            for gpid, _ in groups:
                lib.bf_xla_plan_free(gpid)
            return None
        ok = True
        # The per-edge transport stripe is pinned AT COMPILE TIME, with
        # the same deterministic (window, row) shard the host sender
        # computes — a plan-dispatched edge and a host-dispatched edge
        # always ride the same FIFO, so mixing paths on one edge can
        # never reorder its stream.
        from bluefog_tpu.ops.transport import stripe_for
        n_stripes = int(getattr(d.transport, "n_stripes", 1) or 1)
        for i, ((src, dst), w) in enumerate(grp):
            host, port = d.proc_addr[d.rank_owner[dst]]
            if lib.bf_xla_plan_edge(pid, i, host.encode(), port, op, src,
                                    dst, float(w), win.row_of[src],
                                    stripe_for(name, src, op,
                                               n_stripes)) != 0:
                ok = False
                break
        if not ok:
            lib.bf_xla_plan_free(pid)
            for gpid, _ in groups:
                lib.bf_xla_plan_free(gpid)
            return None
        groups.append((pid, grp))
    wb = _wire_bytes(comp, op, elems)
    plan = PutPlan(name, op, comp, elems, groups, [wb] * len(edge_list),
                   [d.rank_owner[dst] for (_, dst), _ in edge_list])
    with _lock:
        existing = _plan_cache.get(key)
        if existing is not None:
            # Lost a concurrent build race: keep the first insert (its
            # native ids may already be dispatching) and free ours —
            # silently dropping it would leak native plan entries.
            _free_plan(plan)
            return existing
        if len(_plan_cache) >= _PLAN_CACHE_MAX:
            # FIFO bound, like the schedule compile caches: evict the
            # oldest entry (and its native plans).
            old_key = next(iter(_plan_cache))
            _free_plan(_plan_cache.pop(old_key))
        _plan_cache[key] = plan
    return plan


def _free_plan(plan: PutPlan) -> None:
    lib = native.lib()
    if lib is None or not native.has_win_xla():
        return
    for pid, _ in plan.groups:
        try:
            lib.bf_xla_plan_free(pid)
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass


def invalidate(name: Optional[str] = None) -> None:
    """Drop cached plans (one window's, or all) and the native sparse
    error-feedback residuals — called from ``win_free`` and transport
    shutdown, mirroring ``ops/window._drop_ef_residuals``."""
    with _lock:
        keys = [k for k in _plan_cache
                if name is None or k[1] == name]
        plans = [_plan_cache.pop(k) for k in keys]
    for p in plans:
        _free_plan(p)
    lib = native.lib()
    if lib is not None and native.has_win_xla():
        try:
            lib.bf_xla_drop_residuals(None if name is None
                                      else name.encode())
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass


class PlanVanished(ValueError):
    """The native plan id was freed between cache fetch and dispatch
    (FIFO eviction or a concurrent invalidate).  Nothing was sent — the
    executor validates the plan before touching any edge — so the caller
    may rebuild and retry safely."""


def set_group_p(plan_id: int, p_vals: Sequence[float]) -> None:
    """Refresh a native plan's per-edge associated-P masses (push-sum)."""
    arr = (ctypes.c_double * len(p_vals))(*p_vals)
    _lib().bf_xla_plan_set_p(plan_id, arr, len(p_vals))


def take_native_residual(name: str, src: int, dst: int, n: int):
    """Copy-and-erase the native sparse error-feedback residual for one
    edge (None if absent or shape-mismatched) — the host encoder folds
    it in so a put stream that switched FFI→host never strands mass."""
    lib = _lib()
    if lib is None or not native.has_win_xla():
        return None
    buf = np.empty(n, np.float32)
    got = int(lib.bf_xla_take_residual(
        name.encode(), src, dst,
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n))
    return buf if got == n else None


def push_native_residual(name: str, src: int, dst: int,
                         arr: np.ndarray) -> None:
    """Fold a host-side residual into the native store (host→FFI path
    switch: the next native sparse send carries it)."""
    lib = _lib()
    if lib is None or not native.has_win_xla():
        return
    a = np.ascontiguousarray(arr, dtype=np.float32)
    lib.bf_xla_add_residual(
        name.encode(), src, dst,
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), a.size)


def run_group(plan_id: int, tx: int, tensor) -> None:
    """Execute one native plan against ``tensor``'s device buffer —
    the zero-copy dispatch.  Raises on transport failure with the same
    error classes as the host-staged path."""
    lib = _lib()
    total = int(tensor.size)
    keepalive = None
    try:
        tensor.block_until_ready()
        ptr = tensor.unsafe_buffer_pointer()
    except Exception:  # noqa: BLE001 — sharded/foreign array: materialize
        import jax
        keepalive = np.ascontiguousarray(jax.device_get(tensor),
                                         dtype=np.float32)
        count_host_copy(keepalive.nbytes, "device_get")
        ptr = keepalive.ctypes.data
    rc = int(lib.bf_xla_plan_run(plan_id, tx, ptr, total))
    del keepalive
    if rc == 0:
        return
    if rc == -4:
        raise ValueError(
            "window transport: window name exceeds the receiver's "
            "128-byte name field (127 usable bytes)")
    if rc == -9:
        raise PlanVanished(
            "window XLA put: the native plan vanished before dispatch "
            "(cache eviction/invalidate race); nothing was sent")
    if rc == -10:
        raise ValueError(
            "window XLA put: a plan row falls outside the payload buffer "
            "— was the window recreated with a different shape mid-put?")
    raise ConnectionError(
        f"window XLA put: native enqueue failed (code {rc})")


def record_dispatch(plan: PutPlan) -> None:
    """Telemetry parity with ``_send_to_proc``: per-peer-process tx bytes
    and the DCN level accounting, from the plan's build-time-aggregated
    wire sizes (one counter bump per peer process, not per edge)."""
    from bluefog_tpu.utils import telemetry
    if not telemetry.enabled():
        return
    for proc, nbytes in plan.proc_bytes.items():
        telemetry.inc("bf_win_proc_tx_bytes_total", nbytes, proc=proc)
    telemetry.inc("bf_comm_level_bytes_total", plan.total_bytes,
                  level="dcn")
    telemetry.inc("bf_win_xla_puts_total", float(plan.n_edges))


# ---------------------------------------------------------------------------
# Host view / commit re-entry (the other two staging copies)
# ---------------------------------------------------------------------------

def host_view(tensor) -> np.ndarray:
    """Host-addressable numpy view of a device array for the LOCAL edge
    writes and the self-publish — zero-copy on the CPU backend; a
    verified copy counts into ``bf_win_host_copy_bytes_total``."""
    import jax
    try:
        out = np.asarray(jax.device_get(tensor))
    except RuntimeError:
        # Sharded multi-host array: the window layer owns the
        # shard-assembly (and its accounting).
        from bluefog_tpu.ops import window as W
        return W._to_numpy(tensor)
    if _materialize_copied(tensor, out):
        count_host_copy(out.nbytes, "device_get")
    return out


def _materialize_copied(src, out: np.ndarray) -> bool:
    """Best-effort: did materializing ``src`` on the host copy bytes?
    Verified by pointer identity; unverifiable exotic arrays count as a
    copy (they did materialize through host memory)."""
    if out is src:
        return False
    if isinstance(src, np.ndarray):
        return not np.may_share_memory(out, src)
    try:
        return (out.__array_interface__["data"][0]
                != src.unsafe_buffer_pointer())
    except Exception:  # noqa: BLE001 — sharded/older-API arrays
        return True


# "verify": jnp.asarray + per-call alias check (counts real copies);
# "dlpack": sticky fast path once a copying asarray was rescued by a
# zero-copy dlpack view.  Per-call verification matters: aliasing is a
# property of EACH array (alignment), not of the runtime alone, so a
# one-shot probe would mis-count later commits that behave differently.
_commit_mode = ["verify"]


def commit_to_jax(arr: np.ndarray):
    """Re-enter jax with a win_update/collect result — zero-copy where
    the runtime allows (``jnp.asarray`` aliases aligned host arrays on
    CPU jax; otherwise a dlpack view), else a counted copy.  The drain
    side's answer to the put side's pointer dispatch: the combined rows
    never round-trip through a host→device upload."""
    import jax
    import jax.numpy as jnp
    if arr.size == 0:
        return jnp.asarray(arr)
    if _commit_mode[0] == "dlpack":
        try:
            return jax.dlpack.from_dlpack(arr)
        except Exception:  # noqa: BLE001 — drop back to verify-per-call
            _commit_mode[0] = "verify"
    out = jnp.asarray(arr)
    if not _jax_aliases(out, arr):
        if armed():
            try:
                out2 = jax.dlpack.from_dlpack(arr)
                if _jax_aliases(out2, arr):
                    _commit_mode[0] = "dlpack"
                    return out2
            except Exception:  # noqa: BLE001 — capability probe
                pass
        count_host_copy(arr.nbytes, "commit")
    return out


def _jax_aliases(jarr, arr: np.ndarray) -> bool:
    try:
        return jarr.unsafe_buffer_pointer() == arr.ctypes.data
    except Exception:  # noqa: BLE001 — cannot verify: assume copy
        return False


# ---------------------------------------------------------------------------
# In-program lowering (bf_xla_win_put)
# ---------------------------------------------------------------------------

_registered = [False]


def _ensure_registered() -> bool:
    """Register the ``bf_xla_win_put`` FFI target once per process."""
    if _registered[0]:
        return True
    if not native.has_xla_handler():
        return False
    from bluefog_tpu import _compat
    mod = _compat.jax_ffi()
    if mod is None:
        return False
    lib = native.lib()
    with _lock:
        if _registered[0]:
            return True
        mod.register_ffi_target("bf_xla_win_put",
                                mod.pycapsule(lib.bf_xla_win_put),
                                platform="cpu")
        # Donated-buffer passthrough variant (fused step programs);
        # absent from prebuilt cores that predate it — the plain target
        # still registers and the fused path degrades gracefully.
        if hasattr(lib, "bf_xla_win_put_pass"):
            mod.register_ffi_target(
                "bf_xla_win_put_pass",
                mod.pycapsule(lib.bf_xla_win_put_pass),
                platform="cpu")
        # In-program probe (BLUEFOG_TPU_PROBE): the timestamp custom call
        # the fused step threads through its semantic seams.  Same
        # degradation contract as the pass variant.
        if hasattr(lib, "bf_xla_probe"):
            mod.register_ffi_target("bf_xla_probe",
                                    mod.pycapsule(lib.bf_xla_probe),
                                    platform="cpu")
        _registered[0] = True
    return True


def has_passthrough() -> bool:
    """True when the donated-buffer passthrough FFI target is available
    (native core carries ``bf_xla_win_put_pass`` and jax has an FFI
    module)."""
    if not _ensure_registered():
        return False
    try:
        return hasattr(native.lib(), "bf_xla_win_put_pass")
    except Exception:  # noqa: BLE001 — treat load failure as absent
        return False


def has_probe() -> bool:
    """True when the in-program probe FFI target is registered (native
    core carries ``bf_xla_probe`` + the ring symbols and jax has an FFI
    module)."""
    if not _ensure_registered():
        return False
    return native.has_probe()


def xla_probe_program(probe_id: int):
    """A timestamp probe lowered INTO a compiled program: returns
    ``f(x) -> x`` where the output IS the input buffer
    (``input_output_aliases={0: 0}`` — XLA donates it, no copy) and the
    custom call records ``(probe_id, steady-clock ns, counter)`` into the
    native probe ring as a side effect.  Because the caller rethreads its
    value through the probe, the recorded instant is pinned into the
    program's dataflow: XLA cannot hoist the probe above the work that
    produced ``x`` or sink it below the stages that consume the output.
    None when the probe handler is unavailable (the Python stamp fallback
    still works)."""
    if not has_probe():
        return None
    from bluefog_tpu import _compat
    import jax
    mod = _compat.jax_ffi()

    def run(x):
        call = mod.ffi_call(
            "bf_xla_probe",
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            has_side_effect=True,
            input_output_aliases={0: 0})
        return call(x, probe_id=np.int64(probe_id))
    return run


def xla_put_program(plan_id: int, tx: int):
    """The put lowered INTO a compiled program: returns ``f(x) ->
    i32[1]`` status whose XLA custom call executes the SAME native plan
    mid-program — embed it in a jitted step so the transport enqueue
    overlaps the rest of the program's execution.  None when the FFI
    handler or jax FFI module is unavailable (the eager pointer dispatch
    still works)."""
    if not _ensure_registered():
        return None
    from bluefog_tpu import _compat
    import jax
    import jax.numpy as jnp
    mod = _compat.jax_ffi()
    call = mod.ffi_call("bf_xla_win_put",
                        jax.ShapeDtypeStruct((1,), jnp.int32),
                        has_side_effect=True)

    def run(x):
        return call(x, plan_id=np.int64(plan_id), tx=np.int64(tx))
    return run


def xla_put_program_pass(plan_id: int, tx: int):
    """Donated-buffer passthrough form of :func:`xla_put_program`:
    returns ``f(x) -> (x, i32[1] status)`` where the first output IS the
    input buffer (``input_output_aliases={0: 0}`` — XLA donates it, no
    copy).  Downstream stages consume the passthrough output, which makes
    the put a real data dependence inside a fused step program: each
    bucket's put issues exactly when XLA materializes that bucket, and
    the program's remaining math keeps executing around it.  None when
    the handler (or the pass variant of it) is unavailable."""
    if not has_passthrough():
        return None
    from bluefog_tpu import _compat
    import jax
    import jax.numpy as jnp
    mod = _compat.jax_ffi()

    def run(x):
        call = mod.ffi_call(
            "bf_xla_win_put_pass",
            (jax.ShapeDtypeStruct(x.shape, x.dtype),
             jax.ShapeDtypeStruct((1,), jnp.int32)),
            has_side_effect=True,
            input_output_aliases={0: 0})
        return call(x, plan_id=np.int64(plan_id), tx=np.int64(tx))
    return run


def drain_to_device(fn, result_avals, *, ordered: bool = True):
    """Embed a host-side window drain INTO a compiled program: wraps
    ``fn`` (a host callback performing ``win_update``/collect and
    returning numpy/jax arrays matching ``result_avals``) as an ordered
    ``io_callback`` so the drain can run mid-program, its results
    re-entering the program as device buffers (on the CPU backend the
    ``commit_to_jax`` views inside ``fn`` stay zero-copy end to end).
    Returns a callable taking arbitrary token arguments (pass the put
    statuses so the drain data-depends on the puts), or None when this
    jax has no ``io_callback``."""
    try:
        from jax.experimental import io_callback
    except Exception:  # noqa: BLE001 — older jax: host-side drain instead
        return None

    def run(*tokens):
        return io_callback(fn, result_avals, *tokens, ordered=ordered)
    return run


def _reset_for_tests() -> None:
    """Drop every cache (plans, arming, commit-mode probe) — test
    isolation only."""
    global _armed_cache, _warned
    with _lock:
        plans = list(_plan_cache.values())
        _plan_cache.clear()
        _armed_cache = (None, False, None)
        _warned = False
        _commit_mode[0] = "verify"
    for p in plans:
        _free_plan(p)
