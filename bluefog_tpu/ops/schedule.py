"""Topology -> collective-permute schedule compiler.

This is the TPU-native replacement for the reference's entire coordination
machinery: the rank-0 negotiation protocol (BlueFog ``operations.cc:825-1093``),
the graph communicator (``mpi_context.cc:373-395``) and the per-vendor
neighbor-exchange implementations (``mpi_controller.cc:369-525``,
``nccl_controller.cc:643-745``).  Because SPMD programs are statically matched
across devices, none of that run-time matching is needed — a topology compiles
*once* into a list of ``lax.ppermute`` rounds plus weight vectors, and the
jitted step function replays it every iteration at ICI speed.

Decomposition: the edge set of any digraph over ranks ``0..n-1`` is partitioned
by cyclic shift distance ``d = (dst - src) mod n``.  All edges of one distance
form a partial permutation (every src and every dst appears at most once), i.e.
exactly one valid ``ppermute``.  Shift-structured topologies (ring, Exp2,
fully-connected) decompose into full permutations with zero waste; irregular
ones (star, mesh) yield partial rounds where non-participating ranks receive
zeros, which the weight vectors mask out.

Weights are applied *source-side*: round ``r`` communicates
``ppermute(x * send_scale_r[rank])`` and the receiver accumulates unscaled.
This one convention implements receiver-chosen ``src_weights``, sender-chosen
``dst_weights`` (partial send) and push-sum column-stochastic scaling alike,
since schedule weights are compile-time constants known on every device.

Round minimization: the shift-distance decomposition is a *starting point*.
Unless ``BLUEFOG_TPU_SCHEDULE_OPT=0``, every compiled schedule is repacked
by :mod:`bluefog_tpu.ops.schedule_opt` into the König-minimal
``max(max_outdeg, max_indeg)`` rounds (bipartite edge coloring), and the
matrix -> schedule compilation is memoized process-wide on the weight-matrix
bytes, so dynamic phase tables and repeated ``set_topology`` calls never
recompile the same matrix twice.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import Dict, List, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

from bluefog_tpu import topology as topo_mod

__all__ = [
    "CommRound",
    "StaticSchedule",
    "CompiledSchedule",
    "DynamicSchedule",
    "PairGossipSchedule",
    "compile_static",
    "compile_dynamic",
    "compile_pair_gossip",
    "uniform_weights",
    "as_compiled",
    "schedule_provenance",
]


@dataclass(frozen=True, eq=False)
class CommRound:
    """One ``ppermute`` worth of communication.

    ``pairs``      — static (src, dst) list handed to ``lax.ppermute``.
    ``send_scale`` — (n,) array; src multiplies its payload by
                     ``send_scale[src]`` before the permute.  Zero for ranks
                     that do not send this round.
    ``recv_mask``  — (n,) 0/1 array; 1 iff the rank receives this round
                     (ppermute already yields zeros for silent ranks, the mask
                     exists for ops that need explicit participation info,
                     e.g. neighbor_allgather padding).
    ``src_of``     — (n,) int array; src rank feeding each dst this round,
                     -1 when silent.  Consumed by ordered-concat ops.
    """
    pairs: Tuple[Tuple[int, int], ...]
    send_scale: np.ndarray
    recv_mask: np.ndarray
    src_of: np.ndarray

    @cached_property
    def dst_of(self) -> np.ndarray:
        """(n,) int array; dst rank each src feeds this round, -1 when
        silent — the inverse of ``src_of``.  Cached on the round so ops
        with traced weights (``neighbor_allreduce_matrix``) don't rebuild
        an O(n) table per round on every retrace."""
        dst = np.full(len(self.send_scale), -1, dtype=np.int32)
        for s, d in self.pairs:
            dst[s] = d
        return dst


@dataclass(frozen=True, eq=False)
class StaticSchedule:
    """Compiled static topology: ``out = self_scale[i] * x_i + sum_r recv_r``."""
    n: int
    rounds: Tuple[CommRound, ...]
    self_scale: np.ndarray       # (n,)
    indegree: np.ndarray         # (n,) int, self-loop excluded
    outdegree: np.ndarray        # (n,) int, self-loop excluded

    @property
    def max_indegree(self) -> int:
        return int(self.indegree.max(initial=0))

    @property
    def is_regular(self) -> bool:
        return bool((self.indegree == self.indegree[0]).all()
                    and (self.outdegree == self.outdegree[0]).all())

    @cached_property
    def slot_tables(self) -> Tuple[np.ndarray, ...]:
        """Per-round output slot of each receiving rank for ordered concat
        (``neighbor_allgather``): slot = position of the arriving src in
        the receiver's ascending in-neighbor list, -1 when silent.  Cached
        on the schedule so ops retracing against it (new shapes/dtypes)
        don't rebuild O(rounds·n) Python tables per trace — the same
        retrace tax ``CommRound.dst_of`` already pays once."""
        in_nbrs: List[List[int]] = [[] for _ in range(self.n)]
        for rnd in self.rounds:
            for s, d in rnd.pairs:
                in_nbrs[d].append(s)
        for lst in in_nbrs:
            lst.sort()
        tables = []
        for rnd in self.rounds:
            slot = np.full(self.n, -1, dtype=np.int32)
            for dst in range(self.n):
                s = rnd.src_of[dst]
                if s >= 0:
                    slot[dst] = in_nbrs[dst].index(int(s))
            tables.append(slot)
        return tuple(tables)


    def window_plan(self) -> Tuple[Tuple[Tuple[int, float], ...], ...]:
        """Per-source lowering for the one-sided WINDOW executor: entry
        ``s`` is the ``(dst, weight)`` list rank ``s`` pushes each step
        (``win_put``/``win_accumulate`` targets), round structure erased —
        the window transport has no round barrier, only per-peer FIFOs.
        The diagonal (``self_scale``) stays with the combiner.  This is
        the second lowering target a :class:`CompiledSchedule` can
        declare; ``lax.ppermute`` rounds are the first."""
        plan: List[List[Tuple[int, float]]] = [[] for _ in range(self.n)]
        for rnd in self.rounds:
            for s, d in rnd.pairs:
                plan[s].append((d, float(rnd.send_scale[s])))
        return tuple(tuple(p) for p in plan)


@dataclass(frozen=True, eq=False)
class CompiledSchedule(StaticSchedule):
    """First-class compiled schedule artifact.

    A ``StaticSchedule`` plus the metadata that used to live implicitly in
    whichever pipeline stage produced the rounds:

    ``provenance``   — how the rounds were derived: ``naive`` (shift-
                       distance decomposition), ``konig`` (min-round
                       bipartite-coloring repack), ``congestion``
                       (congestion-aware link-load repack) or
                       ``synthesized:<sketch>`` (:mod:`ops/synthesis`).
    ``modeled_cost`` — the :class:`ops.placement.CostReport` the producer
                       priced the rounds at (None when no interconnect
                       model was active — logical-only compilation).
    ``lowering``     — executor the rounds target: ``ppermute`` (rounds
                       become ``lax.ppermute`` calls inside one XLA
                       program) or ``window`` (rounds flatten to the
                       per-peer push plan of :meth:`window_plan`).
    ``sketch``       — the communication sketch a synthesized schedule
                       was grown from (None for non-synthesized).

    It IS a ``StaticSchedule`` (every executor, cache and cost-model
    consumer keeps working on the artifact unchanged); the metadata rides
    along for telemetry (``schedule_wire_stats`` provenance labels), cache
    keying and the ``tools schedule-dump`` inspector.
    """
    provenance: str = "naive"
    modeled_cost: Optional[object] = None
    lowering: str = "ppermute"
    sketch: Optional[str] = None


_UNSET = object()


def as_compiled(sched: StaticSchedule, *, provenance=None, modeled_cost=_UNSET,
                lowering=None, sketch=_UNSET) -> CompiledSchedule:
    """Wrap (or re-tag) a schedule as a :class:`CompiledSchedule` artifact.

    Unspecified fields inherit from ``sched`` when it already is an
    artifact, else take the defaults — so every pipeline stage can stamp
    only the metadata it owns (the König repack stamps provenance, the
    synthesis stamps provenance+sketch+cost) without erasing the rest."""
    prov = provenance if provenance is not None else \
        getattr(sched, "provenance", "naive")
    cost = modeled_cost if modeled_cost is not _UNSET else \
        getattr(sched, "modeled_cost", None)
    low = lowering if lowering is not None else \
        getattr(sched, "lowering", "ppermute")
    sk = sketch if sketch is not _UNSET else getattr(sched, "sketch", None)
    return CompiledSchedule(
        n=sched.n, rounds=sched.rounds, self_scale=sched.self_scale,
        indegree=sched.indegree, outdegree=sched.outdegree,
        provenance=prov, modeled_cost=cost, lowering=low, sketch=sk)


def schedule_provenance(sched) -> str:
    """Provenance tag of any schedule object: the artifact's own tag, a
    ``DynamicSchedule``'s phase consensus (``mixed`` when phases disagree),
    ``naive`` for plain pre-artifact schedules."""
    phases = getattr(sched, "phases", None)
    if phases is not None:
        tags = {schedule_provenance(ph) for ph in phases}
        return tags.pop() if len(tags) == 1 else "mixed"
    return getattr(sched, "provenance", "naive")


@dataclass(frozen=True, eq=False)
class DynamicSchedule:
    """Periodic dynamic topology: step ``t`` runs ``phases[t % len(phases)]``."""
    n: int
    phases: Tuple[StaticSchedule, ...]

    @property
    def period(self) -> int:
        return len(self.phases)

    @property
    def provenance(self) -> str:
        return schedule_provenance(self)


@dataclass(frozen=True, eq=False)
class PairGossipSchedule:
    """Single-round symmetric exchange for ``pair_gossip``."""
    n: int
    round: CommRound
    self_scale: np.ndarray


def _rounds_from_matrix_py(w: np.ndarray) -> Tuple[CommRound, ...]:
    """Partition off-diagonal edges of ``w`` by shift distance into rounds.

    Pure-Python reference implementation (and the test oracle for the native
    one below)."""
    n = w.shape[0]
    by_dist: Dict[int, List[Tuple[int, int]]] = {}
    srcs, dsts = np.nonzero(w)
    for s, d in zip(srcs.tolist(), dsts.tolist()):
        if s == d:
            continue
        by_dist.setdefault((d - s) % n, []).append((s, d))
    rounds = []
    for dist in sorted(by_dist):
        pairs = tuple(sorted(by_dist[dist]))
        send_scale = np.zeros(n)
        recv_mask = np.zeros(n)
        src_of = np.full(n, -1, dtype=np.int32)
        for s, d in pairs:
            send_scale[s] = w[s, d]
            recv_mask[d] = 1.0
            src_of[d] = s
        rounds.append(CommRound(pairs, send_scale, recv_mask, src_of))
    return tuple(rounds)


def _rounds_from_matrix_native(w: np.ndarray) -> Optional[Tuple[CommRound, ...]]:
    """Native-core round decomposition (``schedule.cc``); None if unbuilt."""
    import ctypes

    from bluefog_tpu import native
    lib = native.lib()
    if lib is None:
        return None
    n = w.shape[0]
    if n < 2:
        return ()
    wq = np.ascontiguousarray(w, dtype=np.float64)
    distances = np.empty(n - 1, dtype=np.int32)
    send_scale = np.empty((n - 1, n), dtype=np.float64)
    recv_mask = np.empty((n - 1, n), dtype=np.float64)
    src_of = np.empty((n - 1, n), dtype=np.int32)
    k = lib.bf_rounds_from_matrix(
        n, wq.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        distances.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        send_scale.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        recv_mask.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        src_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    rounds = []
    for r in range(k):
        so = src_of[r]
        dsts = np.nonzero(so >= 0)[0]
        pairs = tuple(sorted((int(so[d]), int(d)) for d in dsts))
        rounds.append(CommRound(pairs, send_scale[r].copy(),
                                recv_mask[r].copy(), so.copy()))
    return tuple(rounds)


def _rounds_from_matrix(w: np.ndarray) -> Tuple[CommRound, ...]:
    native_rounds = _rounds_from_matrix_native(w)
    if native_rounds is not None:
        return native_rounds
    return _rounds_from_matrix_py(w)


def _build_schedule(w: np.ndarray,
                    optimize: Optional[bool] = None) -> StaticSchedule:
    """Uncached matrix -> schedule: naive decomposition + min-round repack.

    ``optimize`` overrides the ``BLUEFOG_TPU_SCHEDULE_OPT`` config flag
    (bench_comm.py and the property tests compile both variants of the
    same matrix to compare them)."""
    from bluefog_tpu.utils import config
    n = w.shape[0]
    off_diag = w.copy()
    np.fill_diagonal(off_diag, 0.0)
    sched = CompiledSchedule(
        n=n,
        rounds=_rounds_from_matrix(w),
        self_scale=np.diag(w).copy(),
        indegree=(off_diag != 0).sum(axis=0).astype(np.int32),
        outdegree=(off_diag != 0).sum(axis=1).astype(np.int32),
        provenance="naive",
    )
    do_opt = config.get().schedule_opt if optimize is None else optimize
    if do_opt:
        from bluefog_tpu.ops.schedule_opt import optimize_schedule
        sched = optimize_schedule(sched)
    return sched


def _schedule_from_matrix(w: np.ndarray) -> StaticSchedule:
    """Matrix -> (optimized) schedule through the process-level compile
    cache — the single funnel ``compile_static``/``compile_dynamic`` use."""
    from bluefog_tpu.ops.schedule_opt import cached_schedule_from_matrix
    return cached_schedule_from_matrix(w, _build_schedule)


def uniform_weights(w_adj: np.ndarray) -> np.ndarray:
    """Replace a 0/1-ish adjacency with uniform ``1/(indeg+1)`` averaging
    weights — the reference's default when topology weights are disabled
    (``torch/mpi_ops.py:433-489``)."""
    n = w_adj.shape[0]
    w = np.zeros_like(w_adj, dtype=float)
    mask = (w_adj != 0)
    np.fill_diagonal(mask, False)
    indeg = mask.sum(axis=0)
    for dst in range(n):
        share = 1.0 / (indeg[dst] + 1.0)
        w[mask[:, dst], dst] = share
        w[dst, dst] = share
    return w


def compile_static(topo: nx.DiGraph, *,
                   use_topo_weights: bool = True,
                   self_weight: Optional[float] = None,
                   src_weights: Optional[np.ndarray] = None) -> StaticSchedule:
    """Compile a static topology into a ppermute schedule.

    ``use_topo_weights=False`` applies uniform ``1/(indeg+1)`` weights (the
    reference's ``bf.init(is_weighted=False)`` default).  ``src_weights`` may
    override the full (n, n) weight matrix; ``self_weight`` overrides the
    diagonal (broadcast to all ranks).
    """
    w = topo_mod.weight_matrix(topo)
    if src_weights is not None:
        w = np.asarray(src_weights, dtype=float)
    elif not use_topo_weights:
        w = uniform_weights(w)
    if self_weight is not None:
        w = w.copy()
        np.fill_diagonal(w, self_weight)
    return _schedule_from_matrix(w)


def _phase_matrix(phase: topo_mod.DynamicPhase, n: int,
                  weights: Optional[np.ndarray] = None) -> np.ndarray:
    """Weight matrix of one dynamic phase: default ``1/(indeg+1)`` averaging."""
    w = np.zeros((n, n))
    if weights is not None:
        for s, d in phase.pairs:
            w[s, d] = weights[s, d]
        np.fill_diagonal(w, np.diag(weights))
        return w
    indeg = np.zeros(n, dtype=np.int64)
    for _s, d in phase.pairs:
        indeg[d] += 1
    for s, d in phase.pairs:
        w[s, d] = 1.0 / (indeg[d] + 1.0)
    for r in range(n):
        w[r, r] = 1.0 / (indeg[r] + 1.0)
    return w


def compile_dynamic(phases: Sequence[topo_mod.DynamicPhase], n: int, *,
                    weights: Optional[np.ndarray] = None) -> DynamicSchedule:
    """Compile a periodic phase table (see ``topology.dynamic_phase_table`` /
    ``one_peer_exp2_phases``) into per-phase static schedules.

    Under ``jit`` the phase is selected with ``lax.switch(t % period)`` over
    branches that each contain their own static ``ppermute`` — dynamic
    topologies never retrace (SURVEY §7 "dynamic topology under jit").
    """
    compiled = [_schedule_from_matrix(_phase_matrix(ph, n, weights))
                for ph in phases]
    return DynamicSchedule(n=n, phases=tuple(compiled))


def compile_pair_gossip(target_of: Sequence[int], n: int, *,
                        self_weight: float = 0.5,
                        target_weight: float = 0.5) -> PairGossipSchedule:
    """Compile a pairwise exchange: ``target_of[i]`` is rank ``i``'s partner
    (must be mutual, ``target_of[target_of[i]] == i``), or -1 to sit out.

    Matches ``bf.pair_gossip`` semantics (reference ``mpi_controller.cc:748-774``
    = ``MPI_Sendrecv`` + average).
    """
    pairs = []
    send_scale = np.zeros(n)
    recv_mask = np.zeros(n)
    src_of = np.full(n, -1, dtype=np.int32)
    self_scale = np.ones(n)
    for i, t in enumerate(target_of):
        if t < 0:
            continue
        assert target_of[t] == i, f"pair_gossip targets must be mutual ({i}<->{t})"
        pairs.append((i, t))
        send_scale[i] = target_weight
        recv_mask[t] = 1.0
        src_of[t] = i
        self_scale[i] = self_weight
    return PairGossipSchedule(
        n=n,
        round=CommRound(tuple(sorted(pairs)), send_scale, recv_mask, src_of),
        self_scale=self_scale,
    )
