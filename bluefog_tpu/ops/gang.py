"""Gossip-native gang join/bootstrap: elastic scale-UP without a coordinator.

PR 7 made the gang shrink (the churn controller commits a survivor epoch
when ranks die); this module is the missing half of "gossip as a service":
capacity follows traffic in BOTH directions, and no single process's death
can take the gang down.

Two halves, both behind ``BLUEFOG_TPU_ELASTIC_JOIN`` (default off — with
the knob off nothing here is ever installed, ``OP_GANG`` frames are
dropped on receipt, and every legacy path is bit-identical):

**Wired join.**  A fresh process (``bfrun --join <endpoint>``) contacts
ANY live member over the window transport's FIFO streams with a
``join_req``; the member grants it a process id plus a set of VACANT
ranks (ranks whose owning process left the gang), chosen where the
placement model prices them cheapest (:func:`choose_admission_ranks`),
and ships the current epoch/view, the endpoint directory, and an
owned-row snapshot of every live window — the same per-process authority
contract ``utils/elastic.py`` and ``run/supervisor._recover`` already
enforce on shrink, applied in the grow direction (the joiner starts from
a survivor's consensus estimate).  The joiner then heartbeats every
member with its admission claim, and the gang commits epoch ``e -> e+1``
with the grown survivor topology through the ordinary all-survivors-agree
rule in ``ops/membership.py`` — join proposals are supersets, suspicion
proposals are subsets, and the two compose in one consensus round.

**Coordinator-free bootstrap.**  A gossip-replicated endpoint directory
(:class:`GangDirectory`: an epoch-versioned rank→endpoint map) replaces
the jax-coordinator KV store for endpoint exchange and membership
rendezvous.  Endpoints are write-once per process id, so the endpoint map
union-merges conflict-free; the (epoch, active, rank_owner) triple adopts
whichever side committed further.  Every process persists its copy
(``BLUEFOG_TPU_GANG_DIR_PATH``: ``<prefix>.<proc>.json``, atomically,
beside ``owned_ranks.json`` when pointed at the checkpoint directory) and
anti-entropy rides ``OP_GANG`` urgent wire ops on the same per-peer FIFO
streams as gossip — killing rank 0's host removes one replica of a
replicated map, not the map.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from bluefog_tpu.utils import config

__all__ = ["GangDirectory", "GangService", "JoinGrant", "parse_peers",
           "choose_admission_ranks", "init_elastic", "join_gang",
           "install", "current", "handle_wire", "health_summary",
           "bootstrap_endpoints"]


def parse_peers(spec: str) -> List[Tuple[str, int]]:
    """Parse ``BFTPU_GANG_PEERS`` (``host:port,host:port,...``, index =
    process id) into a list of endpoints."""
    peers = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        host, sep, port = item.rpartition(":")
        if not sep or not host:
            raise ValueError(f"gang: bad peer endpoint {item!r} "
                             "(expected host:port)")
        peers.append((host, int(port)))
    if not peers:
        raise ValueError("gang: BFTPU_GANG_PEERS is empty")
    return peers


def _ep_str(addr: Tuple[str, int]) -> str:
    return f"{addr[0]}:{addr[1]}"


def _ep_addr(ep: str) -> Tuple[str, int]:
    """``"host:port"`` → ``(host, port)`` — the ONE parse every consumer
    of directory/claim endpoints shares (membership hints and the
    supervisor's growth recovery included)."""
    host, sep, port = ep.rpartition(":")
    if not sep or not host:
        raise ValueError(f"gang: bad endpoint {ep!r} (expected host:port)")
    return (host, int(port))


class GangDirectory:
    """The gossip-replicated endpoint directory: who is in the gang, which
    ranks each process owns, and where its transport listens.

    Merge semantics are CRDT-shaped so replicas converge without
    coordination: ``endpoints`` entries are write-once per proc id (a
    restarted process gets a NEW id, never a recycled one) and
    union-merge; the ``(epoch, active, rank_owner)`` triple is owned by
    the membership consensus and the higher epoch wins wholesale.  A
    same-proc endpoint conflict — only reachable through a cross-grantor
    id race — resolves deterministically to the lexicographically smaller
    endpoint, with a warning."""

    def __init__(self, n_ranks: int, endpoints: Dict[int, str],
                 epoch: int = 0, active=(), rank_owner=None):
        self.n_ranks = int(n_ranks)
        self.endpoints = {int(p): str(e) for p, e in endpoints.items()}
        self.epoch = int(epoch)
        self.active = tuple(sorted(int(p) for p in active))
        self.rank_owner = {int(r): int(p)
                           for r, p in (rank_owner or {}).items()}

    # -- serialization ------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "n_ranks": self.n_ranks,
            "endpoints": {str(p): e
                          for p, e in sorted(self.endpoints.items())},
            "epoch": self.epoch,
            "active": list(self.active),
            "rank_owner": {str(r): p
                           for r, p in sorted(self.rank_owner.items())},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "GangDirectory":
        return cls(d["n_ranks"],
                   {int(p): e for p, e in d.get("endpoints", {}).items()},
                   epoch=d.get("epoch", 0), active=d.get("active", ()),
                   rank_owner={int(r): p
                               for r, p in d.get("rank_owner", {}).items()})

    # -- CRDT merge ---------------------------------------------------------

    def merge(self, other: "GangDirectory") -> bool:
        """Fold another replica in; returns True when anything changed."""
        changed = False
        for p, ep in other.endpoints.items():
            mine = self.endpoints.get(p)
            if mine is None:
                self.endpoints[p] = ep
                changed = True
            elif mine != ep:
                from bluefog_tpu.utils.logging import get_logger
                get_logger().warning(
                    "gang directory: conflicting endpoints for proc %d "
                    "(%s vs %s) — keeping %s (cross-grantor id race?)",
                    p, mine, ep, min(mine, ep))
                if ep < mine:
                    self.endpoints[p] = ep
                    changed = True
        if other.epoch > self.epoch:
            self.epoch = other.epoch
            self.active = tuple(other.active)
            self.rank_owner = dict(other.rank_owner)
            changed = True
        return changed

    def vacant_ranks(self) -> List[int]:
        """Ranks owned by no active process — the admission pool."""
        active = set(self.active)
        return sorted(r for r, p in self.rank_owner.items()
                      if p not in active)

    def live_endpoints(self) -> List[Tuple[str, int]]:
        """Endpoints of the ACTIVE processes (join candidates), active
        order."""
        return [_ep_addr(self.endpoints[p]) for p in self.active
                if p in self.endpoints]

    # -- persistence --------------------------------------------------------

    def persist(self, path: str) -> None:
        """Atomic write (tmp + replace): a reader can never observe a torn
        directory, and a crash mid-write leaves the previous copy."""
        tmp = path + ".tmp"
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(self.to_dict(), fh)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "GangDirectory":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    @classmethod
    def load_any(cls, prefix: str) -> "GangDirectory":
        """Merge every replica persisted under ``<prefix>.<proc>.json``
        (plus a bare ``<prefix>`` file) into one view — the freshest
        committed epoch wins, endpoints union.  This is what a joining
        process bootstraps from: any surviving replica is enough."""
        merged: Optional[GangDirectory] = None
        base = os.path.basename(prefix)
        dirname = os.path.dirname(prefix) or "."
        candidates = []
        try:
            for f in sorted(os.listdir(dirname)):
                if f == base or (f.startswith(base + ".")
                                 and f.endswith(".json")):
                    candidates.append(os.path.join(dirname, f))
        except OSError:
            pass
        for path in candidates:
            try:
                d = cls.load(path)
            except (OSError, ValueError, KeyError):
                continue
            if merged is None:
                merged = d
            else:
                merged.merge(d)
        if merged is None:
            raise FileNotFoundError(
                f"gang: no readable directory replica under {prefix!r}")
        return merged


class JoinGrant:
    """What a live member hands a joining process: identity, the committed
    view, the directory, and the owned-row snapshot to start from."""

    def __init__(self, proc: int, ranks: Tuple[int, ...], epoch: int,
                 active: Tuple[int, ...], directory: GangDirectory,
                 windows: Dict[str, dict], my_endpoint: str):
        self.proc = proc
        self.ranks = tuple(ranks)
        self.epoch = epoch
        self.active = tuple(active)
        self.directory = directory
        # name -> {"shape": tuple, "dtype": str, "rows": {rank: ndarray}}
        self.windows = windows
        self.my_endpoint = my_endpoint


# ---------------------------------------------------------------------------
# Placement-aware admission
# ---------------------------------------------------------------------------

def choose_admission_ranks(vacant, want: int, active_ranks=()) -> List[int]:
    """Pick which vacant ranks a joiner is admitted as.

    With a live interconnect model (``ops/placement.py``), each vacant
    rank is priced by the modeled distance from its (placed) device to
    the active ranks' devices and the cheapest seats win — the new
    capacity lands where ``optimize_placement`` prices it, not wherever
    the joiner happened to boot.  (The full re-plan still runs at the
    grow commit: ``set_topology`` re-enters the placement + synthesis
    pipeline for the grown edge set.)  Without a model: lowest rank ids,
    fully deterministic either way."""
    vacant = sorted(set(int(r) for r in vacant))
    want = max(1, int(want))
    if want >= len(vacant):
        return vacant
    try:
        from bluefog_tpu.ops import placement
        state = placement.active()
    except Exception:  # noqa: BLE001 — pricing is an optimization only
        state = None
    if state is None or state[0] is None:
        return vacant[:want]
    model, perm = state

    def dev(rank: int) -> int:
        return int(perm[rank]) if perm is not None else int(rank)

    peers = [int(r) for r in active_ranks]

    def price(rank: int) -> float:
        if not peers:
            return 0.0
        try:
            return float(sum(model.distance(dev(rank), dev(s))
                             for s in peers))
        except Exception:  # noqa: BLE001 — an out-of-model rank: neutral
            return float("inf")

    return sorted(sorted(vacant), key=lambda r: (price(r), r))[:want]


# ---------------------------------------------------------------------------
# The service: join grants + directory anti-entropy
# ---------------------------------------------------------------------------

_RESERVATION_SEC = 60.0


class GangService:
    """Per-process join/directory service.  Installed (``install()``) when
    ``BLUEFOG_TPU_ELASTIC_JOIN=1`` and a gang transport is live; the
    window drain routes inbound ``OP_GANG`` frames here."""

    def __init__(self, directory: GangDirectory,
                 persist_path: Optional[str] = None):
        cfg = config.get()
        self.directory = directory
        # <prefix>.<proc>.json — per-process replica files, so concurrent
        # writers on one filesystem never race each other.
        self._prefix = (cfg.gang_dir_path if persist_path is None
                        else persist_path)
        self._lock = threading.Lock()
        self._reserved: Dict[int, tuple] = {}  # proc -> (ranks, expiry)
        self.pending_grant: Optional[JoinGrant] = None
        self.grants_total = 0

    # -- plumbing -----------------------------------------------------------

    def _distrib(self):
        from bluefog_tpu.ops import window as W
        return W._store.distrib

    def _my_proc(self) -> Optional[int]:
        d = self._distrib()
        return None if d is None else d.my_proc

    def _send(self, addr: Tuple[str, int], body: dict) -> None:
        from bluefog_tpu.ops.transport import OP_GANG
        d = self._distrib()
        if d is None:
            return
        payload = np.frombuffer(json.dumps(body).encode(), np.uint8)
        d.transport.send(addr[0], addr[1], OP_GANG, "",
                         d.my_rank, -1, 0.0, payload)

    def persist(self) -> None:
        from bluefog_tpu.utils import telemetry
        # Snapshot under the service lock: the drain thread's anti-entropy
        # merges and the supervisor's commit follow-through mutate the
        # directory concurrently, and serializing a dict mid-mutation
        # raises.  The disk write happens on the snapshot, outside.
        with self._lock:
            body = json.dumps(self.directory.to_dict())
            epoch = self.directory.epoch
        telemetry.set_gauge("bf_gang_directory_epoch", epoch)
        if not self._prefix:
            return
        me = self._my_proc()
        path = (f"{self._prefix}.{me}.json" if me is not None
                else f"{self._prefix}.json")
        try:
            tmp = path + ".tmp"
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as fh:
                fh.write(body)
            os.replace(tmp, path)
        except OSError as e:
            from bluefog_tpu.utils.logging import get_logger
            get_logger().warning("gang: directory persist to %s failed: %s",
                                 path, e)

    def announce(self, procs=None) -> None:
        """Anti-entropy push: ship the directory to peers (default: every
        active proc with a known endpoint, except self).  State-based and
        idempotent — duplicates and reordering are harmless."""
        me = self._my_proc()
        with self._lock:
            body = {"k": "dir", "dir": self.directory.to_dict()}
            if procs is None:
                procs = [p for p in self.directory.active if p != me]
            addrs = [_ep_addr(self.directory.endpoints[p]) for p in procs
                     if p in self.directory.endpoints]
        for addr in addrs:
            try:
                self._send(addr, body)
            except Exception:  # noqa: BLE001 — a dead peer is expected
                pass

    # -- inbound dispatch ---------------------------------------------------

    def handle(self, msg: dict) -> None:
        kind = msg.get("k")
        if kind == "dir":
            try:
                other = GangDirectory.from_dict(msg["dir"])
            except (KeyError, ValueError, TypeError):
                return
            with self._lock:
                changed = self.directory.merge(other)
            if changed:
                # Off the drain thread: persist() is disk I/O, and every
                # inbound window message would stall behind a slow
                # (checkpoint-grade NFS) write otherwise.
                from bluefog_tpu.ops import window as W
                W._store.svc_pool.submit(self.persist)
            return
        if kind == "join_req":
            if not config.get().elastic_join:
                self._deny(msg, "BLUEFOG_TPU_ELASTIC_JOIN is off")
                return
            # Grant work (window snapshots under win locks + a reply
            # send) must not run on the drain thread.
            from bluefog_tpu.ops import window as W
            W._store.svc_pool.submit(self._grant, msg)
            return
        if kind in ("grant", "deny"):
            _resolve_join_reply(msg)

    # -- the grant side -----------------------------------------------------

    def _deny(self, msg: dict, reason: str) -> None:
        ep = msg.get("ep")
        if ep:
            try:
                self._send(_ep_addr(ep), {"k": "deny",
                                          "nonce": msg.get("nonce"),
                                          "reason": reason})
            except Exception:  # noqa: BLE001
                pass

    def _grant(self, msg: dict) -> None:
        """Admit one joiner: assign a fresh proc id + placement-priced
        vacant ranks, snapshot the live windows' owned rows, reply with
        the grant, and seed the membership controller so the grow
        proposal starts propagating immediately."""
        from bluefog_tpu.ops import membership
        from bluefog_tpu.ops import window as W
        from bluefog_tpu.utils import telemetry
        ctrl = membership.current()
        joiner_ep = msg.get("ep")
        if not joiner_ep:
            return
        if ctrl is None:
            self._deny(msg, "no membership controller (BLUEFOG_TPU_CHURN "
                            "off?)")
            return
        want = max(1, int(msg.get("want", 1)))
        now = time.monotonic()
        with ctrl._lock:
            epoch = ctrl.epoch
            active = frozenset(ctrl.active)
            rank_owner = dict(ctrl.rank_owner)
            active_ranks = ctrl.active_ranks()
            pending_claimed = {r for info in ctrl.pending_joins.values()
                               for r in info[0]}
            known_procs = (set(rank_owner.values()) | set(active)
                           | set(ctrl.pending_joins)
                           | set(ctrl.joined_info))
        with self._lock:
            self._reserved = {p: v for p, v in self._reserved.items()
                              if v[1] > now}
            reserved_ranks = {r for v in self._reserved.values()
                              for r in v[0]}
            vacant = [r for r, p in rank_owner.items()
                      if p not in active and r not in pending_claimed
                      and r not in reserved_ranks]
            if not vacant:
                pass  # denied below, outside the lock
            else:
                ranks = choose_admission_ranks(vacant,
                                               min(want, len(vacant)),
                                               active_ranks=active_ranks)
                proc = max(known_procs | set(self.directory.endpoints)
                           | {p for p in self._reserved}) + 1
                self._reserved[proc] = (tuple(ranks),
                                        now + _RESERVATION_SEC)
        if not vacant:
            self._deny(msg, "gang is at full strength (no vacant ranks)")
            return
        windows = {}
        donor_note = None
        for name in W.get_current_created_window_names():
            try:
                win = W._store.get(name)
            except KeyError:
                continue
            with win.lock:
                if not win.owned:
                    continue
                donor = win.owned[0]
                rows = {int(r): base64.b64encode(
                            np.ascontiguousarray(
                                win.main[donor]).tobytes()).decode()
                        for r in ranks}
                windows[name] = {"shape": list(win.shape),
                                 "dtype": win.dtype.name, "rows": rows}
                donor_note = donor
        with self._lock:
            body = {
                "k": "grant", "nonce": msg.get("nonce"),
                "proc": proc, "ranks": list(ranks),
                "epoch": epoch, "active": sorted(active),
                "n_ranks": self.directory.n_ranks,
                "rank_owner": {str(r): p
                               for r, p in sorted(rank_owner.items())},
                "endpoints": {str(p): e for p, e in
                              sorted(self.directory.endpoints.items())},
                "windows": windows,
            }
        try:
            self._send(_ep_addr(joiner_ep), body)
        except Exception as e:  # noqa: BLE001 — joiner died mid-handshake
            from bluefog_tpu.utils.logging import get_logger
            get_logger().warning("gang: join grant to %s failed: %s",
                                 joiner_ep, e)
            with self._lock:
                self._reserved.pop(proc, None)
            return
        ctrl.note_join(proc, ranks, joiner_ep)
        self.grants_total += 1
        telemetry.inc("bf_gang_join_grants_total")
        from bluefog_tpu.utils.logging import get_logger
        get_logger().warning(
            "gang: granted join — proc %d takes rank(s) %s (endpoint %s, "
            "window snapshot from rank %s)", proc, list(ranks), joiner_ep,
            donor_note)

    # -- commit follow-through ---------------------------------------------

    def on_commit(self, view, rank_owner: Dict[int, int]) -> None:
        """Fold a committed membership change into the directory (called by
        the supervisor AFTER it updated the transport's maps) and persist
        the new replica."""
        with self._lock:
            self.directory.epoch = view.epoch
            # The consensus view is authoritative (every committed
            # recovery view names its full active set).
            self.directory.active = tuple(view.active_procs)
            self.directory.rank_owner = dict(rank_owner)
            for p, ep in view.added_endpoints.items():
                self.directory.endpoints.setdefault(int(p), ep)
            for p in view.added_procs:
                self._reserved.pop(p, None)
        self.persist()

    def summary(self) -> dict:
        with self._lock:
            return {
                "epoch": self.directory.epoch,
                "n_ranks": self.directory.n_ranks,
                "active_procs": list(self.directory.active),
                "endpoints": len(self.directory.endpoints),
                "vacant_ranks": self.directory.vacant_ranks(),
                "grants_total": self.grants_total,
                "persist_prefix": self._prefix,
            }


# ---------------------------------------------------------------------------
# Process-wide registry (mirrors ops/membership.py's)
# ---------------------------------------------------------------------------

_active_service: Optional[GangService] = None
_registry_lock = threading.Lock()

# Joiner-side grant waiters, keyed by nonce: registered BEFORE the service
# exists (the joining process has no directory yet when the reply lands).
_join_waiters: Dict[str, list] = {}
_waiters_lock = threading.Lock()


def install(svc: Optional[GangService]) -> None:
    global _active_service
    with _registry_lock:
        _active_service = svc


def current() -> Optional[GangService]:
    return _active_service


def _resolve_join_reply(msg: dict) -> None:
    nonce = msg.get("nonce")
    with _waiters_lock:
        waiter = _join_waiters.get(nonce)
    if waiter is not None:
        waiter[1] = msg
        waiter[0].set()


def handle_wire(payload) -> None:
    """Entry point for inbound ``OP_GANG`` frames (window drain thread).
    Dropped silently when neither a service nor a join waiter is
    interested — exactly the OP_MEMBER contract, so a stale frame from a
    peer that still thinks we joined can never crash the drain."""
    try:
        msg = json.loads(bytes(payload).decode())
    except (ValueError, UnicodeDecodeError):
        from bluefog_tpu.utils.logging import get_logger
        get_logger().warning("gang: undecodable OP_GANG frame dropped "
                             "(%d bytes)", len(payload))
        return
    if msg.get("k") in ("grant", "deny"):
        _resolve_join_reply(msg)
    svc = _active_service
    if svc is not None and msg.get("k") != "grant":
        svc.handle(msg)


def health_summary() -> Optional[dict]:
    """The gang-directory block for ``/healthz`` (None when the subsystem
    is not installed)."""
    svc = _active_service
    if svc is None:
        return None
    return svc.summary()


# ---------------------------------------------------------------------------
# Bootstrap entry points
# ---------------------------------------------------------------------------

def bootstrap_endpoints() -> Optional[List[Tuple[str, int]]]:
    """The pre-assigned gang endpoints from ``BFTPU_GANG_PEERS`` (set by
    ``bfrun --elastic``), or None when this launch is not elastic."""
    spec = os.environ.get("BFTPU_GANG_PEERS")
    return parse_peers(spec) if spec else None


def init_elastic(port: Optional[int] = None) -> GangService:
    """Coordinator-free gang bootstrap for one founding member.

    Requires ``bf.init()`` already called over the full virtual world (the
    process sees all ``n`` ranks; ownership is per-process through the
    directory) and ``BFTPU_GANG_PEERS`` in the environment (``bfrun
    --elastic`` pre-assigns one transport port per process and exports the
    full list, so NO key-value exchange — and no coordinator — is needed:
    every process starts with the complete endpoint map and gossip takes
    over from there).  Builds the window transport on this process's
    pinned port, installs the rank directory, and installs + persists the
    gang service."""
    cfg = config.get()
    if not cfg.elastic_join:
        raise RuntimeError(
            "gang.init_elastic requires BLUEFOG_TPU_ELASTIC_JOIN=1 (the "
            "join/bootstrap subsystem must be an explicit operational "
            "decision, never ambient)")
    spec = os.environ.get("BFTPU_GANG_PEERS")
    if not spec:
        raise RuntimeError("gang.init_elastic: BFTPU_GANG_PEERS is not "
                           "set (launch with `bfrun --elastic`)")
    peers = parse_peers(spec)
    my_proc = int(os.environ["BFTPU_PROCESS_ID"])
    from bluefog_tpu import basics
    from bluefog_tpu.ops import window as W
    n = basics.size()
    if n % len(peers):
        raise RuntimeError(
            f"gang.init_elastic: world size {n} is not divisible by the "
            f"{len(peers)}-process gang")
    per = n // len(peers)
    rank_owner = {r: r // per for r in range(n)}
    transport = W.make_transport(
        port=peers[my_proc][1] if port is None else port)
    proc_addr = {p: addr for p, addr in enumerate(peers)}
    W.install_distrib(transport, rank_owner, proc_addr, my_proc)
    directory = GangDirectory(
        n, {p: _ep_str(a) for p, a in proc_addr.items()},
        epoch=0, active=range(len(peers)), rank_owner=rank_owner)
    svc = GangService(directory)
    install(svc)
    svc.persist()
    from bluefog_tpu.utils.logging import get_logger
    get_logger().info(
        "gang: coordinator-free bootstrap — proc %d of %d, ranks %s, "
        "endpoint %s", my_proc, len(peers),
        [r for r, p in rank_owner.items() if p == my_proc],
        _ep_str(peers[my_proc]))
    return svc


def _decode_grant(msg: dict, my_endpoint: str) -> JoinGrant:
    directory = GangDirectory(
        msg["n_ranks"],
        {int(p): e for p, e in msg.get("endpoints", {}).items()},
        epoch=msg.get("epoch", 0), active=msg.get("active", ()),
        rank_owner={int(r): p
                    for r, p in msg.get("rank_owner", {}).items()})
    windows = {}
    for name, w in (msg.get("windows") or {}).items():
        shape = tuple(int(s) for s in w["shape"])
        dtype = np.dtype(w["dtype"])
        rows = {int(r): np.frombuffer(
                    base64.b64decode(b), dtype=dtype).reshape(shape)
                for r, b in (w.get("rows") or {}).items()}
        windows[name] = {"shape": shape, "dtype": dtype.name, "rows": rows}
    return JoinGrant(int(msg["proc"]),
                     tuple(int(r) for r in msg["ranks"]),
                     int(msg.get("epoch", 0)),
                     tuple(int(p) for p in msg.get("active", ())),
                     directory, windows, my_endpoint)


def _probe_addr(addr: Tuple[str, int], timeout: float = 0.75) -> bool:
    import socket
    try:
        socket.create_connection(addr, timeout=timeout).close()
        return True
    except OSError:
        return False


def join_gang(target: str, *, want: Optional[int] = None,
              timeout_ms: Optional[float] = None) -> JoinGrant:
    """Join a live gang as a fresh process.

    ``target`` is any live member's transport endpoint (``host:port``) or
    a persisted directory prefix (``@<prefix>`` — every replica under it
    is merged and each live member is tried in turn; this is the
    coordinator-free path a replacement uses after rank 0's host died).
    Requires ``bf.init()`` over the full virtual world.  On success the
    window transport + rank directory are installed (this process owning
    the granted ranks) and the returned :class:`JoinGrant` carries the
    window snapshot to ``win_create`` from once the grow epoch commits
    (drive a :class:`~bluefog_tpu.run.supervisor.ChurnSupervisor` — it
    seeds itself from the pending grant)."""
    import uuid
    cfg = config.get()
    if not cfg.elastic_join:
        raise RuntimeError(
            "gang.join_gang requires BLUEFOG_TPU_ELASTIC_JOIN=1")
    if want is None:
        # How many vacant ranks to claim: `bfrun --join --join-want N`
        # exports it; default 1.  A replacement for a multi-rank process
        # must ask for that process's whole seat count — a partial claim
        # commits a grow epoch that leaves the gang under strength.
        want = int(os.environ.get("BFTPU_GANG_JOIN_WANT", "1"))
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.ops.transport import OP_GANG
    from bluefog_tpu.utils import telemetry
    wait_sec = (cfg.join_timeout_ms if timeout_ms is None
                else timeout_ms) / 1e3
    if target.startswith("@"):
        directory = GangDirectory.load_any(target[1:])
        candidates = directory.live_endpoints()
    else:
        candidates = [_ep_addr(target)]
    # Cheap TCP pre-filter so a dead member (say, the killed rank 0) costs
    # a sub-second probe, not a full grant timeout.
    live = [a for a in candidates if _probe_addr(a)]
    if not live:
        raise ConnectionError(
            f"gang: no live member endpoint reachable among {candidates}")
    transport = W.make_transport()
    me_ep = f"{W._local_host_addr()}:{transport.port}"
    grant_msg = None
    try:
        for addr in live:
            nonce = uuid.uuid4().hex
            waiter = [threading.Event(), None]
            with _waiters_lock:
                _join_waiters[nonce] = waiter
            body = {"k": "join_req", "nonce": nonce, "ep": me_ep,
                    "want": int(want)}
            try:
                payload = np.frombuffer(json.dumps(body).encode(),
                                        np.uint8)
                transport.send(addr[0], addr[1], OP_GANG, "", -1, -1,
                               0.0, payload)
                if waiter[0].wait(wait_sec) and waiter[1] is not None:
                    msg = waiter[1]
                    if msg.get("k") == "grant":
                        grant_msg = msg
                        break
                    from bluefog_tpu.utils.logging import get_logger
                    get_logger().warning(
                        "gang: join denied by %s:%d — %s", addr[0],
                        addr[1], msg.get("reason"))
            except (ConnectionError, OSError):
                continue
            finally:
                with _waiters_lock:
                    _join_waiters.pop(nonce, None)
    except BaseException:
        transport.stop()
        raise
    if grant_msg is None:
        transport.stop()
        raise TimeoutError(
            f"gang: no member of {live} granted the join within "
            f"{wait_sec:.0f}s per endpoint")
    grant = _decode_grant(grant_msg, me_ep)
    rank_owner = dict(grant.directory.rank_owner)
    for r in grant.ranks:
        rank_owner[r] = grant.proc
    proc_addr = {p: _ep_addr(e)
                 for p, e in grant.directory.endpoints.items()}
    proc_addr[grant.proc] = _ep_addr(me_ep)
    W.install_distrib(transport, rank_owner, proc_addr, grant.proc)
    directory = grant.directory
    directory.endpoints[grant.proc] = me_ep
    svc = GangService(directory)
    svc.pending_grant = grant
    install(svc)
    svc.persist()
    telemetry.inc("bf_gang_joins_requested_total")
    from bluefog_tpu.utils.logging import get_logger
    get_logger().warning(
        "gang: join granted — proc %d takes rank(s) %s at epoch %d "
        "(endpoint %s); awaiting the grow commit", grant.proc,
        list(grant.ranks), grant.epoch, me_ep)
    return grant
