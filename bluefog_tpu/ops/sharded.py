"""Sharding-aware gossip payload planner.

Both optimizer families historically assumed fully replicated parameters:
every rank holds the whole tree, so every leaf rides the raveled gossip
buffer and DCN wire bytes scale with *full* model size.  With the
``bluefog_tpu.parallel`` machinery a tree can instead be a mix of

* **replicated** leaves (data-parallel state — every rank holds the same
  values and gossip should average them across the *whole* topology), and
* **sharded** leaves (expert / pipeline-stage / tensor-parallel kernels —
  each rank owns one slice along a model dimension, and only ranks that
  hold the *same* slice coordinate may average with each other).

This module turns a tree of :class:`jax.sharding.PartitionSpec`-style
model-dimension specs into a :class:`ShardPlan`:

* a per-leaf **gossip mask** (replicated leaves → full-topology buffer,
  sharded leaves → per-replica-group buffer of the rank's *own* slice),
* the **replica groups** — ranks holding identical shard coordinates —
  and each rank's group coordinate, and
* per-group **sub-schedules**, each compiled independently through the
  regular :func:`ops.schedule.compile_static` funnel (König repack,
  congestion/synthesis, process-wide matrix memoization) and then merged
  into one ``n``-rank schedule whose round ``r`` is the disjoint union of
  every group's round ``r`` — disjoint rank supports make the merged
  rounds valid partial permutations, so the existing ``ppermute``
  executors replay them unchanged.

The payoff is the perf headline of the sharded-gossip work: per-step wire
bytes drop to the *replicated fraction* of the tree (sharded slices never
leave their replica group, and each group member ships ``1/n_shards`` of
the sharded bytes), and the modeled serial time of the merged schedule is
priced per group through the same placement pipeline as any other
topology.

Leaves are **rank-major** throughout (leading axis ``n``, one row per
rank, as produced by ``bf.broadcast_parameters``/``tp_shard_params``);
a spec entry at model dimension ``d`` therefore refers to leaf array axis
``1 + d``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import Dict, List, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

from bluefog_tpu.ops import schedule as S

__all__ = [
    "ShardPlan",
    "build_plan",
    "default_groups",
    "group_topology",
    "compile_group_schedules",
    "edge_level_counts",
    "induced_window_weights",
    "own_shard_rows",
    "scatter_shard_rows",
    "record_level_bytes",
]


# ---------------------------------------------------------------------------
# Spec normalization
# ---------------------------------------------------------------------------

def _normalize_spec(spec, model_ndim: int) -> Tuple[Optional[str], ...]:
    """Normalize a model-dim PartitionSpec/tuple to a ``model_ndim``-tuple.

    Entries may be ``None`` (replicated dim), a mesh-axis name, or a tuple
    of names (treated as sharded).  Short specs are padded with ``None``
    on the right, matching ``PartitionSpec`` semantics."""
    if spec is None:
        return (None,) * model_ndim
    entries = tuple(spec)
    if len(entries) > model_ndim:
        entries = entries[:model_ndim]
    entries = entries + (None,) * (model_ndim - len(entries))
    return tuple(e if e else None for e in entries)


def _leaf_bytes(leaf) -> int:
    shape = tuple(getattr(leaf, "shape", ()))
    dtype = np.dtype(getattr(leaf, "dtype", np.float32))
    return int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape \
        else dtype.itemsize


# ---------------------------------------------------------------------------
# The plan artifact
# ---------------------------------------------------------------------------

@dataclass(frozen=True, eq=False)
class ShardPlan:
    """Per-leaf gossip routing decisions for one (tree, sharding) pair.

    ``mask[i]``/``dims[i]`` follow the tree's flatten order: ``mask[i]``
    is True iff leaf ``i`` gossips per replica group, and ``dims[i]`` is
    the *model* dimension it is sharded along (leaf array axis
    ``1 + dims[i]``; ``None`` for replicated leaves).  ``decisions[i]``
    is a human-readable audit string for tooling/BENCH json."""
    n: int
    n_shards: int
    groups: Tuple[Tuple[int, ...], ...]
    coords: Tuple[int, ...]                    # rank -> group index
    mask: Tuple[bool, ...]                     # per leaf, flatten order
    dims: Tuple[Optional[int], ...]            # per leaf, model dim or None
    rep_bytes: int
    sh_bytes: int
    decisions: Tuple[str, ...]

    @property
    def any_sharded(self) -> bool:
        return any(self.mask)

    @property
    def replicated_fraction(self) -> float:
        total = self.rep_bytes + self.sh_bytes
        return 1.0 if total == 0 else self.rep_bytes / total

    @cached_property
    def signature(self) -> Tuple:
        """Hashable token for schedule caches and fused-program keys."""
        return (self.n, self.n_shards, self.groups, self.mask, self.dims)

    def summary(self) -> Dict[str, object]:
        """JSON-friendly description for BENCH detail / schedule-dump."""
        return {
            "n": self.n,
            "n_shards": self.n_shards,
            "groups": [list(g) for g in self.groups],
            "replicated_fraction": round(self.replicated_fraction, 6),
            "replicated_bytes": self.rep_bytes,
            "sharded_bytes": self.sh_bytes,
            "leaves_sharded": int(sum(self.mask)),
            "leaves_total": len(self.mask),
            "decisions": list(self.decisions),
        }


def default_groups(n: int, n_shards: int) -> Tuple[Tuple[int, ...], ...]:
    """Contiguous replica groups: shard ``s`` owns ranks ``[s*g, (s+1)*g)``.

    Contiguous blocks are the layout ``tp_shard_params`` produces on a
    shard-major mesh, and keep in-group edges short on a linear/torus
    interconnect (in-group gossip stays intra-slice)."""
    if n_shards <= 0 or n % n_shards != 0:
        raise ValueError(
            f"default_groups: n={n} not divisible by n_shards={n_shards}")
    g = n // n_shards
    return tuple(tuple(range(s * g, (s + 1) * g)) for s in range(n_shards))


def _validate_groups(n: int, groups) -> Tuple[Tuple[int, ...], ...]:
    norm = tuple(tuple(int(r) for r in g) for g in groups)
    flat = sorted(r for g in norm for r in g)
    if flat != list(range(n)):
        raise ValueError(
            f"replica groups {norm} must partition range({n})")
    return norm


def build_plan(tree, specs, *, n: int, n_shards: Optional[int] = None,
               groups=None) -> ShardPlan:
    """Build the gossip plan for a rank-major ``tree`` under ``specs``.

    ``specs`` is a tree of *model*-dimension PartitionSpecs matching the
    params structure (``tp_param_specs`` output; ``None`` means fully
    replicated).  A leaf is planned *sharded* when its spec names a mesh
    axis on some model dim **and** that dim divides evenly by
    ``n_shards`` — otherwise it falls back to replicated gossip with the
    reason recorded in ``decisions`` (an indivisible dim cannot be
    round-tripped through equal per-coordinate slices)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if specs is None:
        spec_leaves = [None] * len(leaves)
    else:
        spec_leaves = treedef.flatten_up_to(specs)

    mask: List[bool] = []
    dims: List[Optional[int]] = []
    decisions: List[str] = []
    rep_bytes = 0
    sh_bytes = 0
    want_shards = int(n_shards) if n_shards else (
        len(groups) if groups else 0)
    for leaf, spec in zip(leaves, spec_leaves):
        model_ndim = max(len(getattr(leaf, "shape", ())) - 1, 0)
        norm = _normalize_spec(spec, model_ndim)
        sharded_dims = [i for i, e in enumerate(norm) if e is not None]
        nbytes = _leaf_bytes(leaf)
        if not sharded_dims:
            mask.append(False); dims.append(None)
            decisions.append("replicated")
            rep_bytes += nbytes
            continue
        d = sharded_dims[0]
        if want_shards <= 0:
            raise ValueError(
                "build_plan: tree has sharded leaves but neither n_shards "
                "nor groups was given")
        dim_len = leaf.shape[1 + d]
        if dim_len % want_shards != 0:
            mask.append(False); dims.append(None)
            decisions.append(
                f"indivisible(dim={d},len={dim_len},shards={want_shards})"
                "->replicated")
            rep_bytes += nbytes
            continue
        mask.append(True); dims.append(d)
        extra = f",extra_dims={sharded_dims[1:]}" if len(sharded_dims) > 1 \
            else ""
        decisions.append(f"sharded(dim={d}{extra})")
        sh_bytes += nbytes

    if groups is not None:
        norm_groups = _validate_groups(n, groups)
        n_shards = len(norm_groups)
    elif n_shards:
        n_shards = int(n_shards)
        norm_groups = default_groups(n, n_shards)
    else:
        # Fully replicated plan with no grouping requested: a single
        # trivial group keeps the signature stable.  Callers that pass a
        # grouping with an all-replicated tree keep it — the telemetry
        # baseline then classifies edges by the same groups as the
        # sharded runs it is compared against.
        n_shards = 1
        norm_groups = (tuple(range(n)),)
    coords = [0] * n
    for gi, g in enumerate(norm_groups):
        for r in g:
            coords[r] = gi
    return ShardPlan(
        n=n, n_shards=n_shards, groups=norm_groups, coords=tuple(coords),
        mask=tuple(mask), dims=tuple(dims), rep_bytes=rep_bytes,
        sh_bytes=sh_bytes, decisions=tuple(decisions))


# ---------------------------------------------------------------------------
# Per-group schedule compilation
# ---------------------------------------------------------------------------

def group_topology(n: int, groups, builder=None) -> nx.DiGraph:
    """Disjoint union of each group's builder topology over the full
    ``n``-rank world (the ``survivor_topology`` relabeling idiom): group
    members gossip among themselves, singleton groups self-loop with
    weight 1.  The union's weight matrix is block doubly stochastic, so
    every existing executor/pricing consumer accepts it unchanged."""
    from bluefog_tpu import topology as topology_util
    if builder is None:
        builder = topology_util.ExponentialTwoGraph
    groups = _validate_groups(n, groups)
    topo = nx.DiGraph()
    topo.add_nodes_from(range(n))
    for g in groups:
        sub = builder(len(g))
        sub = nx.relabel_nodes(sub, dict(enumerate(g)), copy=True)
        topo.add_weighted_edges_from(
            (s, d, w.get("weight", 1.0)) for s, d, w in sub.edges(data=True))
    for r in range(n):
        if topo.out_degree(r) == 0:
            topo.add_edge(r, r, weight=1.0)
    return topo


def _relabel_round(rnd: S.CommRound, ranks: Sequence[int], n: int) \
        -> S.CommRound:
    pairs = tuple((ranks[s], ranks[d]) for s, d in rnd.pairs)
    send = np.zeros(n)
    recv = np.zeros(n)
    src = np.full(n, -1, dtype=np.int32)
    idx = np.asarray(ranks)
    send[idx] = rnd.send_scale
    recv[idx] = rnd.recv_mask
    for ld in range(len(ranks)):
        ls = int(rnd.src_of[ld])
        if ls >= 0:
            src[ranks[ld]] = ranks[ls]
    return S.CommRound(pairs=pairs, send_scale=send, recv_mask=recv,
                       src_of=src)


def compile_group_schedules(n: int, groups, builder=None,
                            use_topo_weights: bool = True):
    """Compile each replica group's sub-topology independently, then merge.

    Every group goes through the full :func:`schedule.compile_static`
    funnel on its own ``|g|``-node topology (so identical groups hit the
    process-wide matrix memo, and König/congestion/synthesis price each
    sub-topology independently).  Round ``r`` of the merged schedule is
    the union of every group's round ``r`` relabeled to global ranks —
    the groups' rank supports are disjoint, so each merged round remains
    a valid partial permutation for ``lax.ppermute``.

    Returns ``(merged, per_group)`` where ``per_group`` is a tuple of
    ``(ranks, CompiledSchedule)`` for tooling (``schedule-dump``)."""
    from bluefog_tpu import topology as topology_util
    if builder is None:
        builder = topology_util.ExponentialTwoGraph
    groups = _validate_groups(n, groups)
    per_group = []
    for g in groups:
        sub_topo = builder(len(g))
        sub = S.compile_static(sub_topo, use_topo_weights=use_topo_weights)
        per_group.append((g, sub))

    n_rounds = max((len(sub.rounds) for _, sub in per_group), default=0)
    self_scale = np.ones(n)
    indeg = np.zeros(n, dtype=np.int64)
    outdeg = np.zeros(n, dtype=np.int64)
    relabeled: List[List[S.CommRound]] = []
    for g, sub in per_group:
        idx = np.asarray(g)
        self_scale[idx] = sub.self_scale
        indeg[idx] = sub.indegree
        outdeg[idx] = sub.outdegree
        relabeled.append([_relabel_round(r, g, n) for r in sub.rounds])

    rounds = []
    for r in range(n_rounds):
        pairs: List[Tuple[int, int]] = []
        send = np.zeros(n)
        recv = np.zeros(n)
        src = np.full(n, -1, dtype=np.int32)
        for rs in relabeled:
            if r >= len(rs):
                continue
            rnd = rs[r]
            pairs.extend(rnd.pairs)
            send += rnd.send_scale
            recv += rnd.recv_mask
            src = np.where(rnd.src_of >= 0, rnd.src_of, src)
        rounds.append(S.CommRound(
            pairs=tuple(sorted(pairs)), send_scale=send, recv_mask=recv,
            src_of=src))

    merged = S.as_compiled(
        S.StaticSchedule(n=n, rounds=tuple(rounds), self_scale=self_scale,
                         indegree=indeg, outdegree=outdeg),
        provenance="sharded")
    return merged, tuple(per_group)


def edge_level_counts(coords: Sequence[int], sched) -> Tuple[float, float]:
    """(in-group, cross-group) directed edge counts of a schedule.

    Replica-group-relative levels: an edge between ranks of the same
    group is "ici" (intra-slice), between groups "dcn".  For a
    ``DynamicSchedule`` the per-phase counts are averaged, matching the
    per-step expectation the byte accounting integrates."""
    phases = getattr(sched, "phases", None)
    if phases is not None:
        counts = [edge_level_counts(coords, ph) for ph in phases]
        return (float(np.mean([c[0] for c in counts])),
                float(np.mean([c[1] for c in counts])))
    ici = dcn = 0
    for rnd in sched.rounds:
        for s, d in rnd.pairs:
            if s == d:
                continue
            if coords[s] == coords[d]:
                ici += 1
            else:
                dcn += 1
    return float(ici), float(dcn)


# ---------------------------------------------------------------------------
# Window lowering: in-group induced edges + matching update weights
# ---------------------------------------------------------------------------

def induced_window_weights(plan: ShardPlan, topo: nx.DiGraph):
    """Restrict the full window topology to in-group edges.

    Returns ``(put_edges, self_weight, nbr_weights)``:

    * ``put_edges`` — ``{(src, dst): 1.0}`` for every full-topology edge
      whose endpoints share a replica group (the sharded window's
      ``dst_weights``; excluded edges are simply never put),
    * ``self_weight`` — per-rank ``1 / (g_indeg + 1)`` vector, and
    * ``nbr_weights`` — ``{(dst, src): 1/(g_indeg+1)}`` for
      ``win_update``; edges absent from the dict leave their staging
      buffers pending, so a neighbor outside the group can never leak
      into the sharded average even if it erroneously puts."""
    coords = plan.coords
    put_edges: Dict[Tuple[int, int], float] = {}
    in_group_srcs: List[List[int]] = [[] for _ in range(plan.n)]
    for s, d in topo.edges():
        if s == d:
            continue
        if coords[s] == coords[d]:
            put_edges[(int(s), int(d))] = 1.0
            in_group_srcs[int(d)].append(int(s))
    self_weight = np.array(
        [1.0 / (len(in_group_srcs[r]) + 1) for r in range(plan.n)])
    nbr_weights = {
        (d, s): float(self_weight[d])
        for d in range(plan.n) for s in in_group_srcs[d]}
    return put_edges, self_weight, nbr_weights


# ---------------------------------------------------------------------------
# Host-side slice helpers (window payloads / fused-step host put)
# ---------------------------------------------------------------------------

def own_shard_rows(leaf: np.ndarray, dim: int, coords: Sequence[int],
                   n_shards: int) -> np.ndarray:
    """Per-rank own-shard slices of a rank-major leaf, flattened to rows.

    ``leaf`` is ``(n, *model)``; row ``r`` of the result is rank ``r``'s
    slice along model dim ``dim`` (array axis ``1 + dim``) for its group
    coordinate, raveled — the sharded window's payload rows."""
    leaf = np.asarray(leaf)
    n = leaf.shape[0]
    axis = 1 + dim
    chunk = leaf.shape[axis] // n_shards
    rows = []
    for r in range(n):
        c = coords[r]
        sl = [slice(None)] * leaf.ndim
        sl[0] = r
        sl[axis] = slice(c * chunk, (c + 1) * chunk)
        rows.append(leaf[tuple(sl)].reshape(-1))
    return np.stack(rows, axis=0)


def scatter_shard_rows(leaf: np.ndarray, rows: np.ndarray, dim: int,
                       coords: Sequence[int], n_shards: int) -> np.ndarray:
    """Inverse of :func:`own_shard_rows`: write combined slice rows back
    into a copy of ``leaf`` (each rank's own coordinate only — the other
    coordinates' values are that rank's stale ghosts and stay put)."""
    leaf = np.asarray(leaf).copy()
    n = leaf.shape[0]
    axis = 1 + dim
    chunk = leaf.shape[axis] // n_shards
    for r in range(n):
        c = coords[r]
        sl = [slice(None)] * leaf.ndim
        sl[0] = r
        sl[axis] = slice(c * chunk, (c + 1) * chunk)
        shape = leaf[tuple(sl)].shape
        leaf[tuple(sl)] = np.asarray(rows[r]).reshape(shape)
    return leaf


# ---------------------------------------------------------------------------
# Telemetry
# ---------------------------------------------------------------------------

def record_level_bytes(plan: ShardPlan, *, rep_ici_edges: float,
                       rep_dcn_edges: float, grp_edges: float,
                       compression: str = "none") -> None:
    """Record one comm step's wire bytes into the level/shard breakdown.

    Levels are replica-group-relative (in-group = "ici", cross-group =
    "dcn").  Replicated leaves ride every full-topology edge; sharded
    leaves ride only in-group edges, and each member ships ``1/n_shards``
    of the sharded tree — so the ``dcn`` series scales with the
    replicated fraction only, which is exactly the invariant the
    ``--sharded`` smoke asserts."""
    from bluefog_tpu.utils import config, telemetry
    if not telemetry.enabled():
        return
    factor = config.compression_byte_factor(compression)
    rep_row = plan.rep_bytes / max(plan.n, 1)
    if rep_ici_edges:
        telemetry.inc("bf_comm_level_bytes_total",
                      rep_row * rep_ici_edges * factor,
                      level="ici", shard="replicated")
    if rep_dcn_edges:
        telemetry.inc("bf_comm_level_bytes_total",
                      rep_row * rep_dcn_edges * factor,
                      level="dcn", shard="replicated")
    if grp_edges and plan.sh_bytes:
        sh_row = plan.sh_bytes / max(plan.n, 1) / max(plan.n_shards, 1)
        telemetry.inc("bf_comm_level_bytes_total",
                      sh_row * grp_edges * factor,
                      level="ici", shard="sharded")
