"""DCN window transport: host-to-host one-sided gossip over TCP.

Python face of ``native/src/winsvc.cc``.  In multi-host runs each process
starts one ``WindowTransport``; ``win_put``/``win_accumulate`` targeting a
rank owned by another host serialize the payload through the native client,
and the peer's service thread queues it until the drain loop applies it to
the local window store's staging buffers — the same observable semantics as
the in-process path (versions, mutexes, associated-P).

This is the structural analogue of the reference's NCCL window machinery
(``nccl_controller.cc:1113-1238``): a passive service answering one-sided
requests, with the control plane folded into the data message (no MPI
request/ack/done handshake needed because TCP already orders and backpressures
the stream).
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Callable

import numpy as np

from bluefog_tpu import native

# Wire op codes — the single source of truth for the window protocol.  The
# native layer carries ``op`` opaquely; codes beyond put/accumulate are
# interpreted purely in Python (ops/window.py documents field use per op).
OP_PUT = 1
OP_ACCUMULATE = 2
OP_GET_REQ = 3
OP_GET_REPLY = 4
OP_FENCE_REQ = 5
OP_FENCE_ACK = 6
OP_MUTEX_ACQ = 7
OP_MUTEX_GRANT = 8
OP_MUTEX_REL = 9
# Flag bit ORed into the op byte when the payload is bf16-compressed (an f32
# window row shipped as bfloat16).  An explicit wire flag — never inferred
# from payload size — so a future partial-row or batched payload can't be
# silently misdecoded as compressed data.
OP_BF16_FLAG = 0x40

__all__ = ["WindowTransport", "OP_PUT", "OP_ACCUMULATE", "OP_GET_REQ",
           "OP_GET_REPLY", "OP_FENCE_REQ", "OP_FENCE_ACK", "OP_MUTEX_ACQ",
           "OP_MUTEX_GRANT", "OP_MUTEX_REL", "OP_BF16_FLAG"]

_OP_NAMES = {OP_PUT: "put", OP_ACCUMULATE: "accumulate",
             OP_GET_REQ: "get_req", OP_GET_REPLY: "get_reply",
             OP_FENCE_REQ: "fence_req", OP_FENCE_ACK: "fence_ack",
             OP_MUTEX_ACQ: "mutex_acq", OP_MUTEX_GRANT: "mutex_grant",
             OP_MUTEX_REL: "mutex_rel"}


def _op_label(op: int) -> str:
    """Telemetry label for a wire op code (compression flag stripped)."""
    return _OP_NAMES.get(op & ~OP_BF16_FLAG, str(op))


class WindowTransport:
    """One per-process TCP endpoint for window gossip.

    ``apply(op, name, src, dst, weight, p_weight, payload)`` is invoked on the
    drain thread for every inbound message; the window store supplies it.
    """

    def __init__(self, apply: Callable, *, port: int = 0,
                 max_pending: int = 4096, drain_interval: float = 0.002):
        self._lib = native.lib()
        if self._lib is None:
            raise RuntimeError(
                "native core unavailable; build with `make -C "
                "bluefog_tpu/native` (or use single-host windows)")
        self._svc = self._lib.bf_winsvc_start(port, max_pending)
        if not self._svc:
            raise OSError(f"cannot start window service on port {port}")
        self._apply = apply
        self._interval = drain_interval
        self._stop = threading.Event()
        self._buf = np.empty(1 << 20, dtype=np.uint8)  # grows on demand
        self._drainer = threading.Thread(target=self._drain, daemon=True,
                                         name="bf-win-transport")
        self._drainer.start()

    @property
    def port(self) -> int:
        return int(self._lib.bf_winsvc_port(self._svc))

    # -- outbound ----------------------------------------------------------
    def send(self, host: str, port: int, op: int, name: str, src: int,
             dst: int, weight: float, tensor: np.ndarray,
             p_weight: float = 0.0) -> None:
        from bluefog_tpu.utils import telemetry
        payload = np.ascontiguousarray(tensor).view(np.uint8).reshape(-1)
        # Guard BEFORE building labels: the disabled path must not pay the
        # per-message f-string/op-name allocations on the gossip hot path.
        t0 = None
        if telemetry.enabled():
            telemetry.inc("bf_win_tx_msgs_total", op=_op_label(op))
            telemetry.inc("bf_win_tx_bytes_total", float(payload.size),
                          peer=f"{host}:{port}")
            t0 = time.perf_counter()
        rc = self._lib.bf_winsvc_send(
            host.encode(), port, op, name.encode(), src, dst,
            float(weight), float(p_weight),
            payload.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            payload.size)
        if t0 is not None:
            # Per-message RPC latency: serialize + connect/enqueue on the
            # native client (TCP backpressure shows up here as tail mass).
            # Guarded so the disabled path skips the label build too.
            telemetry.observe_since(t0, "bf_win_rpc_seconds",
                                    op=_op_label(op))
        if rc != 0:
            if telemetry.enabled():
                telemetry.inc("bf_win_tx_errors_total",
                              peer=f"{host}:{port}")
            raise ConnectionError(
                f"win transport send to {host}:{port} failed (code {rc})")

    # -- inbound -----------------------------------------------------------
    def _drain(self):
        from bluefog_tpu.utils import telemetry
        msg = native.WinMsg()
        burst = 0  # consecutive non-empty recvs: inbound-queue depth proxy
        burst_t0 = 0.0
        while not self._stop.is_set():
            got = self._lib.bf_winsvc_recv(
                self._svc, ctypes.byref(msg),
                self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                self._buf.size)
            if got == -1:  # payload larger than buffer: grow and retry
                self._buf = np.empty(max(self._buf.size * 2, 1 << 24),
                                     dtype=np.uint8)
                continue
            if got == 0:
                if burst:
                    # The native layer exposes no queue-length API, so the
                    # burst length — messages drained back-to-back before
                    # the queue ran dry — is the depth proxy.
                    telemetry.set_gauge("bf_win_rx_queue_depth", burst)
                    # Burst service time: how long the drain thread spent
                    # applying back-to-back messages before the queue ran
                    # dry — tail mass here means inbound gossip arrives
                    # faster than this host applies it.
                    telemetry.observe("bf_win_drain_burst_seconds",
                                      time.perf_counter() - burst_t0)
                    burst = 0
                self._stop.wait(self._interval)
                continue
            if not burst:
                burst_t0 = time.perf_counter()
            burst += 1
            if telemetry.enabled():  # skip label rendering when off
                telemetry.inc("bf_win_rx_msgs_total",
                              op=_op_label(int(msg.op) & ~OP_BF16_FLAG))
                telemetry.inc("bf_win_rx_bytes_total",
                              float(msg.payload_len))
            payload = bytes(self._buf[:msg.payload_len])
            try:
                self._apply(int(msg.op), msg.name.decode(), int(msg.src),
                            int(msg.dst), float(msg.weight),
                            float(msg.p_weight), payload)
            except Exception:  # noqa: BLE001 — drain thread must survive
                import logging
                logging.getLogger("bluefog_tpu").exception(
                    "window transport apply failed")

    def stop(self):
        self._stop.set()
        self._drainer.join(timeout=5)
        if self._svc:
            self._lib.bf_winsvc_stop(self._svc)
            self._svc = None
