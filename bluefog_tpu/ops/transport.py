"""DCN window transport: host-to-host one-sided gossip over TCP.

Python face of ``native/src/winsvc.cc``.  In multi-host runs each process
starts one ``WindowTransport``; ``win_put``/``win_accumulate`` targeting a
rank owned by another host serialize the payload through the native client,
and the peer's service thread queues it until the drain loop applies it to
the local window store's staging buffers — the same observable semantics as
the in-process path (versions, mutexes, associated-P).

This is the structural analogue of the reference's NCCL window machinery
(``nccl_controller.cc:1113-1238``): a passive service answering one-sided
requests, with the control plane folded into the data message (no MPI
request/ack/done handshake needed because TCP already orders and backpressures
the stream).

Coalescing (default on, ``BLUEFOG_TPU_WIN_COALESCE=0`` restores the legacy
per-message path): ``send()`` enqueues onto a bounded per-peer queue serviced
by one sender worker per peer — parallel across neighbors, blocking
backpressure when full.  A worker flushes its queue as a single ``OP_BATCH``
wire frame (version-flagged sub-message stream, many puts in one native
send) on a byte threshold, a short linger timeout, an "urgent" op (fence /
mutex / get traffic), or an explicit :meth:`WindowTransport.flush` that
window ops call at op boundaries.  Because EVERY message to a peer rides
that peer's queue and the worker writes batches in enqueue order over the
one pooled TCP connection, per-peer FIFO — the property ``win_fence`` and
the distributed mutex rely on — is exactly preserved: a FENCE_REQ enqueued
after puts is decoded after them from the same batch stream.  Small
per-parameter gossip rows then cost wire time per BYTE, not per message
(HiCCL's aggregation argument, arxiv 2408.05962).

Native hot path (``BLUEFOG_TPU_WIN_NATIVE``, default on): the whole hot
loop above — per-peer queues, sender workers, OP_BATCH frame encode,
inbound batch decode, the bf16/sparse payload codecs, and the same-slot
drain folding — runs in the C++ core (``native/src/winsvc.cc``,
``bf_wintx_*`` / ``bf_winsvc_drain``) instead of Python threads under the
GIL: ``send()`` is one ctypes call into a C++ per-peer queue, and the
drain thread receives ONE already-folded commit set per ``win.lock`` hold
instead of per-message Python decode work.  The Python implementation in
this module is kept fully intact as the ``BLUEFOG_TPU_WIN_NATIVE=0``
fallback AND the equivalence oracle (same wire frames, bit-identical
folded state — ``tests/test_transport_batch.py``); the native path
auto-falls back to it whenever the ``.so`` is missing, stale, or predates
the ``bf_wintx`` symbols.

Multi-stream striping (``BLUEFOG_TPU_WIN_STRIPES``, default auto): every
peer endpoint is driven by N independent sockets + sender workers + send
arenas (both hot paths), with frames sharded deterministically by
(window, row) — each stripe is an independent FIFO, so same-slot ordering
is preserved per stripe while a single fat DCN link is saturated by N
parallel streams instead of one.  Fences and mutex releases fan out
across all stripes of the addressed peer and complete only when every
stripe has drained (``ops/window.py`` counts the copies); ``auto`` sizes
N from the placement model's ``dcn_link_cost`` and stays at 1 — the
bitwise single-stream wire behavior — on flat hosts.  The drain side
gains a small decode pool (``BLUEFOG_TPU_WIN_DECODE_THREADS``): inbound
frames from different connections decode/scale/fold in parallel C++
workers while the drain emits in exact arrival order.
"""

from __future__ import annotations

import ctypes
import struct
import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu import native
from bluefog_tpu.utils import config, flightrec

# Wire op codes — the single source of truth for the window protocol.  The
# native layer carries ``op`` opaquely; codes beyond put/accumulate are
# interpreted purely in Python (ops/window.py documents field use per op).
OP_PUT = 1
OP_ACCUMULATE = 2
OP_GET_REQ = 3
OP_GET_REPLY = 4
OP_FENCE_REQ = 5
OP_FENCE_ACK = 6
OP_MUTEX_ACQ = 7
OP_MUTEX_GRANT = 8
OP_MUTEX_REL = 9
# Container frame: the payload is a version-flagged stream of sub-messages
# (see _encode_batch), many one-sided ops shipped in ONE native send.  Never
# combined with OP_BF16_FLAG at the frame level — compression is a per-sub-
# message property, carried on each sub-message's own op byte.
OP_BATCH = 10
# Membership control plane (ops/membership.py): heartbeat / proposal /
# view JSON payloads of the churn controller.  Rides the same per-peer
# FIFO streams as gossip, so a peer whose data path is wedged cannot look
# healthy through a side channel the data never takes.
OP_MEMBER = 11
# Gang join/bootstrap control plane (ops/gang.py): join requests/grants
# and the gossip-replicated endpoint-directory anti-entropy of the
# elastic scale-UP subsystem (BLUEFOG_TPU_ELASTIC_JOIN).  JSON payloads
# on the same FIFO streams as gossip and membership — a joining process
# rendezvouses with ANY live member over the data path itself, so no
# coordinator (and no rank-0 host) is load-bearing for bootstrap.
OP_GANG = 12
# Flag bit ORed into the op byte when the payload is bf16-compressed (an f32
# window row shipped as bfloat16).  An explicit wire flag — never inferred
# from payload size — so a future partial-row or batched payload can't be
# silently misdecoded as compressed data.
OP_BF16_FLAG = 0x40
# Flag bit ORed into the op byte when the payload is a top-|magnitude|
# sparse row (``win_compression=sparse:<frac>``): a self-describing
# ``u32 k | i32 idx[k] | f32 val[k]`` stream (see sparse_encode) the
# receiver scatters back into a zero row.  Explicit on the wire for the
# same reason as OP_BF16_FLAG — never inferred from payload size.
OP_SPARSE_FLAG = 0x20
# Flag bit ORed into the op byte when the payload carries a wire trace
# tag: a 32-byte ``i32 src_rank | u32 seq | i64 origin_monotonic_us |
# i64 origin_unix_us | i64 origin_step`` trailer APPENDED to the
# (possibly compressed) payload, on a sampled subset of puts/accumulates
# (``BLUEFOG_TPU_TRACE_SAMPLE=1/N``; default off — no flag, no trailer,
# the wire bitwise identical).  Riding inside the payload means the tag
# survives OP_BATCH framing, the bf16/sparse codecs and striping with no
# further protocol: every decoder strips it by this flag before codec
# validation.
OP_TRACE_FLAG = 0x10
# Every wire-flag bit the base op code must be masked with before
# comparing against the OP_* constants.
OP_FLAG_MASK = OP_BF16_FLAG | OP_SPARSE_FLAG | OP_TRACE_FLAG

__all__ = ["WindowTransport", "OP_PUT", "OP_ACCUMULATE", "OP_GET_REQ",
           "OP_GET_REPLY", "OP_FENCE_REQ", "OP_FENCE_ACK", "OP_MUTEX_ACQ",
           "OP_MUTEX_GRANT", "OP_MUTEX_REL", "OP_BATCH", "OP_MEMBER",
           "OP_GANG", "OP_BF16_FLAG", "OP_SPARSE_FLAG", "OP_TRACE_FLAG",
           "OP_FLAG_MASK", "TRACE_TRAILER", "make_trace_tag",
           "trace_strip", "set_trace_origin_step", "trace_origin_step",
           "sparse_encode", "sparse_decode", "stripe_for",
           "resolve_stripes"]

_OP_NAMES = {OP_PUT: "put", OP_ACCUMULATE: "accumulate",
             OP_GET_REQ: "get_req", OP_GET_REPLY: "get_reply",
             OP_FENCE_REQ: "fence_req", OP_FENCE_ACK: "fence_ack",
             OP_MUTEX_ACQ: "mutex_acq", OP_MUTEX_GRANT: "mutex_grant",
             OP_MUTEX_REL: "mutex_rel", OP_BATCH: "batch",
             OP_MEMBER: "member", OP_GANG: "gang"}

# Ops whose latency is on a waiter's critical path (fence acks, mutex
# grants, get replies): they flush the peer's queue immediately instead of
# waiting out the linger, and — being enqueued AFTER any pending data —
# certify that data once answered (the FIFO property win_fence needs).
# Membership messages are urgent too: a heartbeat sitting out a linger
# behind a slow batch would read as churn where there is none.  Gang
# join/directory traffic likewise — a join grant waiting out a linger
# would stretch every admission by the coalesce window for no benefit.
_URGENT_OPS = frozenset((OP_GET_REQ, OP_GET_REPLY, OP_FENCE_REQ,
                         OP_FENCE_ACK, OP_MUTEX_ACQ, OP_MUTEX_GRANT,
                         OP_MUTEX_REL, OP_MEMBER, OP_GANG))


def _op_label(op: int) -> str:
    """Telemetry label for a wire op code (compression flags stripped)."""
    return _OP_NAMES.get(op & ~OP_FLAG_MASK, str(op))


# ---------------------------------------------------------------------------
# Wire trace tags (OP_TRACE_FLAG / BLUEFOG_TPU_TRACE_SAMPLE)
# ---------------------------------------------------------------------------
# A sampled subset of data messages carries a compact identity + origin
# timestamp, so one put can be followed from dispatch through arena →
# stripe → wire → drain → fold → commit (the trace-gossip tool joins the
# per-rank flight-recorder dumps into cross-rank flow arrows) and every
# fold can be given an AGE (bf_win_contribution_age_seconds — the sensor
# a bounded-staleness async mode will read).  Sequence spaces are
# disjoint between the encoders: Python tags count up from 1, the native
# XLA-plan encoder (bf_trace_next) sets bit 31 — one process's
# (src_rank, seq) pair is globally unique either way.

# src_rank, seq, mono_us, unix_us, origin_step (-1 = sender had no step
# clock — pre-async senders, raw transport users).
TRACE_TRAILER = struct.Struct("<iIqqq")

_trace_lock = threading.Lock()
_trace_count = 0
_trace_seq = 0
# The sender's current training step (the async step clock): published
# by the window optimizer family each step so sampled messages carry an
# EXACT origin step and the receiver's staleness bound can count in
# steps instead of estimating from wall clocks.  -1 = unknown.
_origin_step = -1


def set_trace_origin_step(step: int) -> None:
    """Publish the sender-side origin-step clock (both encoders: this
    module's :func:`make_trace_tag` and, when the native core is live,
    the XLA put plans' ``bf_trace_next``)."""
    global _origin_step
    _origin_step = int(step)
    from bluefog_tpu import native
    handle = native.lib()
    if handle is not None and hasattr(handle, "bf_trace_set_step"):
        handle.bf_trace_set_step(int(step))


def trace_origin_step() -> int:
    return _origin_step


def make_trace_tag(src: int) -> Optional[bytes]:
    """Sampling decision + trailer for one outgoing data message: the
    packed 32-byte trailer when this message is the 1-in-N tagged one,
    else None.  With ``BLUEFOG_TPU_TRACE_SAMPLE`` unset this is one
    config-flag check — no counter mutation, no allocation (the
    bitwise-identical-wire guarantee)."""
    period = config.get().trace_sample
    if period <= 0:
        return None
    global _trace_count, _trace_seq
    with _trace_lock:
        count = _trace_count
        _trace_count += 1
        if count % period:
            return None
        _trace_seq += 1
        seq = _trace_seq
    return TRACE_TRAILER.pack(src, seq, time.monotonic_ns() // 1000,
                              time.time_ns() // 1000, _origin_step)


def trace_strip(payload) -> Tuple["bytes | memoryview",
                                  Tuple[int, int, int, int, int]]:
    """Split a tagged payload into ``(body, (src_rank, seq,
    origin_monotonic_us, origin_unix_us, origin_step))``.  Raises
    ValueError when the payload cannot carry its trailer (malformed frame
    — per-message isolation handles it exactly like any other bad
    payload)."""
    n = len(payload)
    if n < TRACE_TRAILER.size:
        raise ValueError(
            f"trace-flagged payload of {n} bytes cannot carry the "
            f"{TRACE_TRAILER.size}-byte trailer")
    tag = TRACE_TRAILER.unpack_from(payload, n - TRACE_TRAILER.size)
    return payload[:n - TRACE_TRAILER.size], tag


# ---------------------------------------------------------------------------
# Multi-stream striping (BLUEFOG_TPU_WIN_STRIPES)
# ---------------------------------------------------------------------------
# Every peer endpoint is driven by N independent sockets + sender workers
# + send arenas; wire frames shard DETERMINISTICALLY by (window, row) so
# each stripe is an independent FIFO.  Same-slot ordering (consecutive
# puts/accumulates into one (window, src) row) is preserved because the
# shard key pins an edge's messages to one stripe; fences and mutex
# releases fan out across all stripes of the addressed peer and complete
# only when every stripe has drained (ops/window.py owns that counting).
# Data ops shard; control singles (GET traffic, mutex ACQ/GRANT, fence
# ACKs, membership heartbeats) ride stripe 0, whose FIFO they never
# needed relative to data anyway.

_DATA_OPS = frozenset((OP_PUT, OP_ACCUMULATE, OP_GET_REPLY))
_crc_cache: Dict[str, int] = {}


def stripe_for(name: str, src: int, op: int, n_stripes: int) -> int:
    """Deterministic transport stripe of one wire message: data ops shard
    by (window, row = src rank), everything else pins stripe 0.  Pure
    function of its arguments (crc32, not ``hash``) so every dispatch
    path — Python sender, native sender, compiled XLA put plans — routes
    one edge's traffic onto the same FIFO."""
    if n_stripes <= 1 or (op & ~OP_FLAG_MASK) not in _DATA_OPS:
        return 0
    crc = _crc_cache.get(name)
    if crc is None:
        crc = _crc_cache[name] = zlib.crc32(name.encode())
    return (crc + (src if src > 0 else 0)) % n_stripes


def resolve_stripes() -> int:
    """The effective stripe count: an explicit ``BLUEFOG_TPU_WIN_STRIPES``
    wins; otherwise the static oracle (:func:`resolve_stripes_static`),
    overridden by the self-tuning control plane's measured-goodput
    derivation when ``BLUEFOG_TPU_TUNE`` has adapted it — the static
    constant prices a DCN crossing the model *assumed*, the tuner prices
    the streams the link *measured* (a measured-idle DCN collapses back to
    one).  With TUNE off the override table is empty and the static value
    passes through bitwise."""
    cfg = config.get()
    if cfg.win_stripes >= 1:
        return cfg.win_stripes
    static = resolve_stripes_static()
    from bluefog_tpu.utils import tuner
    return max(1, min(8, tuner.override_int("stripes", static)))


def resolve_stripes_static() -> int:
    """The static ``auto`` oracle: the placement model's ``dcn_link_cost``
    (a DCN crossing modeled k× an ICI hop gets ~k parallel streams,
    capped at 8 — the HiCCL sizing argument), and flat hosts / no model
    stay at 1, the bitwise single-stream wire behavior."""
    try:
        from bluefog_tpu import basics
        model = basics._ctx._placement_state[0]
    except Exception:  # noqa: BLE001 — pre-init transports (chaos gangs)
        model = None
    if model is None:
        return 1
    return max(1, min(8, int(round(float(model.dcn_link_cost)))))


def _resolve_decode_threads() -> int:
    """Drain-decode pool size: explicit knob wins; ``auto`` leaves one
    core for the drain/apply thread and floors at 1 — even a single
    worker pipelines decode ahead of the Python apply — capped at 4
    (decode is memory-bound well before that)."""
    cfg = config.get()
    if cfg.win_decode_threads >= 0:
        return cfg.win_decode_threads
    import os
    return max(1, min(4, (os.cpu_count() or 2) - 1))


# Payload size above which the ctypes-fallback send passes the RAW data
# pointer instead of tobytes(): below it, bytes→char* is ctypes' cheapest
# conversion and the copy is ~free; above it the byte copy dwarfs the ~µs
# pointer-extraction cost it was avoiding (the copy scales with the row,
# the pointer does not).  Covered by a unit test in tests/test_win_xla.py.
CTYPES_PTR_BYTES = 64 * 1024


def _ctypes_payload(tensor: np.ndarray):
    """Payload argument for ``bf_wintx_send``'s ctypes binding (declared
    ``c_void_p``, which accepts both forms): ``(arg, nbytes, keepalive)``
    — bytes below :data:`CTYPES_PTR_BYTES`, the raw ``.ctypes`` address
    above it.  ``keepalive`` must stay referenced until the call returns
    (the native side copies into its arena synchronously)."""
    t = tensor if (tensor.__class__ is np.ndarray
                   and tensor.flags.c_contiguous) \
        else np.ascontiguousarray(tensor)
    if t.nbytes >= CTYPES_PTR_BYTES:
        return t.ctypes.data, t.nbytes, t
    from bluefog_tpu.ops import xlaffi
    xlaffi.count_host_copy(t.nbytes, "enqueue")
    return t.tobytes(), t.nbytes, t


# ---------------------------------------------------------------------------
# sparse:<frac> payload codec (OP_SPARSE_FLAG)
# ---------------------------------------------------------------------------
# Layout (little-endian): u32 k | k x i32 flat-index | k x f32 value.
# Self-describing (k on the wire), so the decoder validates the byte count
# exactly and a truncated or mis-flagged payload is an explicit error,
# never a silently mis-scattered row.  Values ride as raw f32 bits — the
# codec is bit-exact on what it sends; the loss lives entirely in the
# sender's top-|magnitude| selection (whose complement the sender keeps as
# an error-feedback residual, see ops/window.py).

_SPARSE_HDR = struct.Struct("<I")


def sparse_encode(values: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Encode selected entries of a flat f32 row as one sparse payload."""
    idx = np.ascontiguousarray(indices, dtype=np.int32)
    val = np.ascontiguousarray(values, dtype=np.float32)
    if idx.shape != val.shape or idx.ndim != 1:
        raise ValueError("sparse_encode expects matching 1-D index/value "
                         f"arrays, got {idx.shape} / {val.shape}")
    blob = (_SPARSE_HDR.pack(len(idx)) + idx.tobytes() + val.tobytes())
    return np.frombuffer(blob, np.uint8)


def sparse_decode(payload) -> Tuple[np.ndarray, np.ndarray]:
    """Decode one sparse payload back to ``(indices, values)`` — bit-exact
    (the f32 bits round-trip untouched through any framing, OP_BATCH
    included)."""
    buf = payload if isinstance(payload, (bytes, bytearray, memoryview)) \
        else memoryview(np.ascontiguousarray(payload, np.uint8)).cast("B")
    (k,) = _SPARSE_HDR.unpack_from(buf, 0)
    want = _SPARSE_HDR.size + k * 8
    if len(buf) != want:
        raise ValueError(
            f"sparse payload of {len(buf)} bytes does not match header "
            f"k={k} (expected {want})")
    off = _SPARSE_HDR.size
    idx = np.frombuffer(buf, np.int32, count=k, offset=off)
    val = np.frombuffer(buf, np.float32, count=k, offset=off + k * 4)
    return idx, val


# ---------------------------------------------------------------------------
# OP_BATCH framing
# ---------------------------------------------------------------------------
# Batch payload layout (little-endian), carried inside one ordinary wire
# frame whose op byte is OP_BATCH:
#   u8 version (=1) | u32 count | count x sub-message
#   sub-message := u8 op | i32 src | i32 dst | f64 weight | f64 p_weight |
#                  u16 name_len | name | u64 payload_len | payload
# The sub-message layout deliberately mirrors the native single-message
# frame (minus the magic), so the two paths stay trivially comparable; the
# version byte means a future layout change is an explicit negotiation
# failure, never a silent misdecode.

BATCH_VERSION = 1
_BATCH_HDR = struct.Struct("<BI")          # version, count
_SUB_HDR = struct.Struct("<BiiddH")        # op, src, dst, weight, p_w, nlen
_SUB_PLEN = struct.Struct("<Q")            # payload_len

# One queued/decoded message: (op, name, src, dst, weight, p_weight,
# payload) with payload any bytes-like (bytes on the send side, a zero-copy
# memoryview into the recv buffer on the drain side).
Msg = Tuple[int, str, int, int, float, float, "bytes | memoryview"]


def _encode_batch(msgs: Sequence[Msg]) -> bytes:
    """Serialize sub-messages into one OP_BATCH payload."""
    parts: List[bytes] = [_BATCH_HDR.pack(BATCH_VERSION, len(msgs))]
    for (op, name, src, dst, weight, p_weight, payload) in msgs:
        nb = name.encode()
        parts.append(_SUB_HDR.pack(op, src, dst, weight, p_weight, len(nb)))
        parts.append(nb)
        parts.append(_SUB_PLEN.pack(len(payload)))
        parts.append(payload)
    return b"".join(parts)


def _decode_batch(buf) -> List[Msg]:
    """Decode one OP_BATCH payload.  ``buf`` is any bytes-like; sub-message
    payloads are returned as ZERO-COPY slices of it (``memoryview`` in when
    memoryview comes in) — valid only as long as the caller keeps ``buf``
    stable, i.e. for the duration of the apply call."""
    ver, count = _BATCH_HDR.unpack_from(buf, 0)
    if ver != BATCH_VERSION:
        raise ValueError(
            f"window batch frame version {ver} != {BATCH_VERSION} — peer "
            "runs an incompatible transport (refusing to guess the layout)")
    off = _BATCH_HDR.size
    out: List[Msg] = []
    for _ in range(count):
        op, src, dst, weight, p_weight, nlen = _SUB_HDR.unpack_from(buf, off)
        off += _SUB_HDR.size
        name = bytes(buf[off:off + nlen]).decode()
        off += nlen
        (plen,) = _SUB_PLEN.unpack_from(buf, off)
        off += _SUB_PLEN.size
        out.append((op, name, src, dst, weight, p_weight,
                    buf[off:off + plen]))
        off += plen
    if off != len(buf):
        raise ValueError(
            f"window batch frame: {len(buf) - off} trailing bytes after "
            f"{count} sub-messages — corrupt or mismatched framing")
    return out


# ---------------------------------------------------------------------------
# Outbound: per-peer sender workers
# ---------------------------------------------------------------------------

class _PeerSender:
    """One bounded queue + one worker thread per (peer endpoint, stripe).

    Parallel across peers (a slow neighbor only stalls its own queue) AND
    across stripes of one peer (N independent streams drive one fat DCN
    link), FIFO within a stripe (one worker, one pooled native
    connection).  The worker flushes on: queue bytes >= the coalesce
    threshold, an urgent control op, an explicit flush(), or the linger
    timeout — whichever comes first."""

    def __init__(self, transport: "WindowTransport", host: str, port: int,
                 stripe: int = 0):
        self._t = transport
        self.host, self.port = host, port
        self.stripe = stripe
        self.peer = f"{host}:{port}"
        self.cond = threading.Condition()
        self.q: deque = deque()           # of Msg; guarded by cond
        self.bytes_pending = 0
        self.flush_now = False
        self.closing = False
        self.error: Optional[Exception] = None
        # Monotonic count of failed batch sends TO THIS PEER.  A dropped
        # batch may have carried several ops' messages and the stored
        # ``error`` reaches only the first flusher; ops snapshot the sum
        # over their peers (transport.error_token) before sending and
        # flush(since=token) raises for every op that overlapped the
        # failure — scoped per peer, so a dead neighbor never fails ops
        # that only addressed healthy ones.
        self.err_count = 0
        # Point-in-time flush markers: messages ever enqueued / messages
        # whose batch send has completed (successfully or dropped — the
        # error paths report drops).  flush() waits for ITS snapshot of
        # seq_enq, not for an empty queue, so concurrent producers on a
        # slow peer cannot starve it past its own messages' departure.
        self.seq_enq = 0
        self.seq_done = 0
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"bf-win-tx-{self.peer}#{stripe}")
        self.thread.start()

    def enqueue(self, msg: Msg, urgent: bool) -> None:
        with self.cond:
            if self.error is not None:
                err, self.error = self.error, None
                raise err
            # Backpressure: a full queue blocks the CALLER (the window
            # worker pool), exactly like the blocking native send did —
            # gossip is never dropped, the producer is paced.
            while (len(self.q) >= self._t._tx_queue_max
                   and not self.closing and self.error is None):
                self.cond.wait(0.05)
            if self.error is not None:
                err, self.error = self.error, None
                raise err
            if self.closing:
                # The worker may already have exited: an append now would
                # sit in the queue forever and read as sent.
                raise ConnectionError(
                    f"win transport to {self.peer} is stopping; message "
                    "not sent")
            self.q.append(msg)
            self.seq_enq += 1
            self.bytes_pending += len(msg[6])
            if urgent or self.bytes_pending >= self._t._flush_bytes:
                self.flush_now = True
            self.cond.notify_all()
        if flightrec.enabled():
            op, name = msg[0], msg[1]
            seq = 0
            if op & OP_TRACE_FLAG and len(msg[6]) >= TRACE_TRAILER.size:
                seq = TRACE_TRAILER.unpack_from(
                    msg[6], len(msg[6]) - TRACE_TRAILER.size)[1]
            flightrec.note(flightrec.ENQUEUE, op=op, stripe=self.stripe,
                           src=msg[2], dst=msg[3], seq=seq,
                           length=len(msg[6]), name=name)

    def flush(self, timeout: float) -> None:
        """Block until everything enqueued BEFORE this call has been
        handed to the native send (TCP kernel buffer) — the coalesced
        path's equivalent of the legacy blocking ``send()`` returning.
        Point-in-time: messages other producers enqueue while we wait do
        not extend the wait."""
        with self.cond:
            target = self.seq_enq
            if self.q:
                # Only arm flush_now with work pending: the flag is reset
                # at drain time, so setting it on an empty queue would
                # make the NEXT message skip its linger and ship as an
                # uncoalesced singleton.
                self.flush_now = True
            self.cond.notify_all()
            ok = self.cond.wait_for(
                lambda: self.error is not None or self.seq_done >= target
                or self.closing,
                timeout=timeout)
            if self.error is not None:
                err, self.error = self.error, None
                raise err
            if self.seq_done >= target:
                return
            if self.closing:
                # stop() raced this flush.  The worker drains its queue
                # before exiting, so give it the same grace stop()'s join
                # allows; if the messages still were not handed off, the
                # contract is "handed to TCP or raises".
                self.cond.wait_for(
                    lambda: self.error is not None
                    or self.seq_done >= target,
                    timeout=min(5.0, timeout))
                if self.error is not None:
                    err, self.error = self.error, None
                    raise err
                if self.seq_done >= target:
                    return
                raise ConnectionError(
                    f"win transport to {self.peer} stopped with "
                    f"{target - self.seq_done} message(s) unsent")
            if not ok:
                raise ConnectionError(
                    f"win transport flush to {self.peer} timed out after "
                    f"{timeout:.0f}s ({len(self.q)} messages still queued)")

    def stop(self) -> None:
        with self.cond:
            self.closing = True
            self.cond.notify_all()
        self.thread.join(timeout=5)

    def _run(self) -> None:
        from bluefog_tpu.utils import telemetry
        linger = self._t._linger
        while True:
            with self.cond:
                while not self.q and not self.closing:
                    self.cond.wait()
                if not self.q:
                    return  # closing with a drained queue
                if not self.flush_now and linger > 0:
                    # Linger briefly so back-to-back edge sends coalesce.
                    # wait_for, not a bare wait: every enqueue notifies
                    # this condition, and only an urgent op / threshold
                    # crossing / close may cut the linger short — a paced
                    # producer must not collapse it to "until the next
                    # message".
                    self.cond.wait_for(
                        lambda: self.flush_now or self.closing,
                        timeout=linger)
                # Drain up to the byte threshold, not the whole queue: a
                # backlog built while the peer backpressured must not
                # become one multi-GB frame (encode copy here, recv-buffer
                # doubling at the peer) — residual messages go next round.
                batch: List[Msg] = []
                nbytes = 0
                while self.q and (not batch
                                  or nbytes < self._t._flush_bytes):
                    m = self.q.popleft()
                    batch.append(m)
                    nbytes += len(m[6])
                self.bytes_pending -= nbytes
                self.flush_now = bool(self.q)  # keep draining a backlog
                self.cond.notify_all()  # wake backpressured producers
            try:
                self._t._send_frames(self.host, self.port, batch,
                                     stripe=self.stripe)
            except Exception as e:  # noqa: BLE001 — surfaced to callers
                import logging
                logging.getLogger("bluefog_tpu").warning(
                    "window transport: batch of %d message(s) to %s "
                    "dropped: %s", len(batch), self.peer, e)
                # The moment the black box matters most: a dropped batch
                # is the canonical "wedged stripe" postmortem input.
                flightrec.dump_on_error(
                    f"batch send to {self.peer} dropped")
                with self.cond:
                    self.error = e
                    self.err_count += 1
            finally:
                with self.cond:
                    # Advance past dropped batches too: their flushers are
                    # woken by `error` first (the predicate checks it
                    # before seq_done), so a drop can never read as a
                    # silent success for the op that owned it.
                    self.seq_done += len(batch)
                    if telemetry.enabled():
                        # Residual backlog AFTER the drain: 0 when the
                        # sender keeps up, pinned near the queue bound
                        # when this peer backpressures us.  Per-stripe:
                        # an imbalanced shard shows up as one hot stripe.
                        telemetry.set_gauge("bf_win_tx_queue_depth",
                                            len(self.q), peer=self.peer,
                                            stripe=str(self.stripe))
                    self.cond.notify_all()


class WindowTransport:
    """One per-process TCP endpoint for window gossip.

    ``apply(op, name, src, dst, weight, p_weight, payload)`` is invoked on
    the drain thread for every inbound message; the window store supplies
    it.  ``payload`` is a ZERO-COPY view into the transport's recv buffer,
    valid only for the duration of the call — ``apply`` must copy anything
    it keeps.  ``apply_batch(msgs)``, when supplied, receives one decoded
    OP_BATCH frame as a list of such messages (arrival order); without it,
    batches fall back to per-message ``apply`` calls.

    ``apply_items(items)``, when supplied AND the native hot path is
    active, receives the native drain's ordered item list: tuples
    ``(0, msg)`` for raw messages (``msg`` exactly as ``apply`` takes it,
    payload a zero-copy view) and ``(1, commit)`` for folded commit
    entries ``(name, replace, src, dst, p_mass, puts, accs, values,
    wire_bytes, trace)`` with ``values`` a zero-copy f32 view valid only
    for the call and ``trace`` the last folded wire trace tag
    ``(src_rank, seq, origin_monotonic_us, origin_unix_us)`` or None.
    Windows opt into native folding via :meth:`register_window`;
    unregistered traffic always arrives raw.
    """

    def __init__(self, apply: Callable, *, apply_batch: Callable = None,
                 apply_items: Callable = None, port: int = 0,
                 max_pending: int = 4096, drain_interval: float = 0.002):
        self._lib = native.lib()
        if self._lib is None:
            raise RuntimeError(
                "native core unavailable; build with `make -C "
                "bluefog_tpu/native` (or use single-host windows)")
        self._svc = self._lib.bf_winsvc_start(port, max_pending)
        if not self._svc:
            raise OSError(f"cannot start window service on port {port}")
        self._apply = apply
        self._apply_batch = apply_batch
        self._apply_items = apply_items
        self._interval = drain_interval
        cfg = config.get()
        self.coalesce = bool(cfg.win_coalesce)
        self._linger = max(0.0, cfg.win_coalesce_linger_ms) / 1e3
        self._flush_bytes = max(1, cfg.win_coalesce_bytes)
        self._tx_queue_max = max(1, cfg.win_tx_queue)
        self._retries = max(0, cfg.win_retries)
        self._retry_backoff = max(0.0, cfg.win_retry_backoff_ms) / 1e3
        # Message-level observability: arm the flight recorder
        # (BLUEFOG_TPU_FLIGHT_RECORDER) and publish the trace-tag
        # sampling period to the native encoders (the XLA put plans tag
        # in C via bf_trace_next; the Python sender tags through
        # make_trace_tag).  Both default off — zero wire/state change.
        from bluefog_tpu.utils import flightrec
        flightrec.maybe_enable()
        if hasattr(self._lib, "bf_trace_configure"):
            self._lib.bf_trace_configure(int(cfg.trace_sample))
        # Multi-stream striping: N sockets + sender workers + send arenas
        # per peer, frames sharded by (window, row).  1 (the no-model
        # auto default) is the bitwise single-stream wire behavior.
        self.n_stripes = resolve_stripes()
        # Peers declared unreachable by chaos fault injection: sends fail
        # immediately, nothing rides the wire (set_partition).
        self._partitioned: frozenset = frozenset()
        # Chaos link-delay fault (set_send_delay): seconds slept before
        # each DATA enqueue, landing between the window layer's trace-tag
        # stamp and the wire — so the observatory measures it as one-way
        # delay, exactly like a slow link.  0.0 (always, outside chaos)
        # is one float truthiness check on the send path.
        self._send_delay = 0.0
        self._senders: Dict[Tuple[str, int, int], _PeerSender] = {}
        self._senders_lock = threading.Lock()
        # Cumulative coalescing stats behind one lock: sender workers on
        # several threads update them, and a racy read-modify-write would
        # drift the ratio gauge.
        self._stats_lock = threading.Lock()
        # Cumulative coalescing inputs for the ratio gauge (sub-messages
        # per native send, 1.0 = no coalescing happening).
        self._tx_frames = 0
        self._tx_msgs = 0
        # -- native hot path (BLUEFOG_TPU_WIN_NATIVE) -----------------------
        # The whole coalesce/encode/decode/fold loop moves into the C++
        # core; the Python classes above stay as the =0 fallback and the
        # equivalence oracle.  Auto-fallback: a missing/stale .so or one
        # predating the bf_wintx symbols pins the Python path.
        self.native_path = (self.coalesce and bool(cfg.win_native)
                            and native.has_win_native())
        self._tx = None
        self.decode_threads = 0
        if self.native_path:
            self._tx = self._lib.bf_wintx_start(
                self._flush_bytes, int(self._linger * 1e6),
                self._tx_queue_max, self._retries, self._retry_backoff,
                self.n_stripes)
            if not self._tx:
                self.native_path = False
        if self.native_path:
            # Encoded host/name caches: the per-message fast path must be
            # one FFI call, not per-call .encode() allocations.  The
            # METH_FASTCALL module (built alongside the .so) cuts the
            # FFI cost ~5x vs ctypes AND takes the payload zero-copy via
            # the buffer protocol; ctypes stays as the everywhere
            # fallback.
            fc = native.fastcall()
            self._fc_send = fc.wintx_send if fc is not None else None
            self._tx_send = self._lib.bf_wintx_send
            self._hostb: Dict[str, bytes] = {}
            self._nameb: Dict[str, bytes] = {}
            self._peer_addrs: set = set()
            self._tx_last = native.WinTxStats()
            self._tx_pump_last = 0.0  # rate-limits the stats pump
            self._rx_last = native.WinRxStats()
            self._peer_last: Dict[Tuple[str, int], Tuple] = {}
            self._stripe_last: Dict[Tuple[str, int, int], int] = {}
            # Drain buffers (grown on demand): ordered item array, raw
            # payload bytes, folded f32 values.
            self._items_cap = 512
            self._items = (native.WinItem * self._items_cap)()
            self._raw_buf = np.empty(1 << 20, dtype=np.uint8)
            self._val_buf = np.empty(1 << 18, dtype=np.float32)
            # Drain-side decode pool: inbound frames from different
            # connections (and different stripes of one peer) decode,
            # scale and fold in parallel C++ workers; bf_winsvc_drain
            # emits in exact arrival order, so the fence/mutex FIFO
            # contract is untouched (0 = inline decode, bit-identical).
            self.decode_threads = int(self._lib.bf_winsvc_set_decode(
                self._svc, _resolve_decode_threads()))
            from bluefog_tpu.utils import telemetry
            telemetry.set_gauge("bf_win_native_active", 1)
        self._stop = threading.Event()
        self._buf = np.empty(1 << 20, dtype=np.uint8)  # grows on demand
        self._drainer = threading.Thread(target=self._drain, daemon=True,
                                         name="bf-win-transport")
        self._drainer.start()

    @property
    def port(self) -> int:
        return int(self._lib.bf_winsvc_port(self._svc))

    # -- native window registry (drain-side folding) -------------------------
    def register_window(self, name: str, elems: int) -> None:
        """Opt a window into the native drain fold path: a flat f32 row of
        ``elems`` elements.  No-op on the Python path; non-f32 windows must
        simply not register (their messages arrive raw)."""
        if self.native_path and elems > 0 and len(name.encode()) < 128:
            self._lib.bf_winsvc_win_set(self._svc, name.encode(), elems)

    def unregister_window(self, name: str) -> None:
        if self.native_path:
            self._lib.bf_winsvc_win_set(self._svc, name.encode(), -1)

    # -- outbound ----------------------------------------------------------
    def send(self, host: str, port: int, op: int, name: str, src: int,
             dst: int, weight: float, tensor: np.ndarray,
             p_weight: float = 0.0, stripe: Optional[int] = None) -> None:
        if stripe is None:
            # Deterministic (window, row) shard: an edge's whole message
            # stream rides ONE stripe FIFO.  Explicit stripes come from
            # the fence/mutex fan-out (ops/window.py), which must address
            # every stripe of a peer.
            stripe = stripe_for(name, src, op, self.n_stripes)
        if self._send_delay and (op & ~OP_FLAG_MASK) in _DATA_OPS:
            # Data ops only: heartbeats, fences, mutex and gang traffic
            # must never be delayed — a chaos link-delay fault models a
            # slow DATA link, not a dead control plane (delaying
            # membership heartbeats would turn every delay experiment
            # into a churn-suspicion experiment).
            time.sleep(self._send_delay)
        if self._tx is not None:
            # Native fast path: ONE ctypes call — enqueue onto the C++
            # per-peer queue (blocking backpressure in C, GIL released).
            # No per-message Python allocations beyond the payload bytes:
            # host/name encodings are cached, telemetry is pumped from the
            # native counters at flush boundaries instead of per message.
            hb = self._hostb.get(host)
            if hb is None:
                hb = self._hostb[host] = host.encode()
                self._peer_addrs.add((host, port))
            nb = self._nameb.get(name)
            if nb is None:
                nb = self._nameb[name] = name.encode()
            urgent = 1 if (op & ~OP_FLAG_MASK) in _URGENT_OPS else 0
            if self._fc_send is not None:
                # METH_FASTCALL path: the payload rides the buffer
                # protocol — zero-copy for a contiguous ndarray, one
                # enqueue-side copy total (into the C++ arena).
                try:
                    rc = self._fc_send(self._tx, hb, port, op, nb, src,
                                       dst, float(weight), float(p_weight),
                                       tensor, urgent, stripe)
                except (BufferError, TypeError):
                    blob = np.ascontiguousarray(tensor).tobytes()
                    from bluefog_tpu.ops import xlaffi
                    xlaffi.count_host_copy(len(blob), "enqueue")
                    rc = self._fc_send(
                        self._tx, hb, port, op, nb, src, dst,
                        float(weight), float(p_weight), blob, urgent,
                        stripe)
            else:
                # ctypes fallback: tobytes() for small rows (bytes→char*
                # is ctypes' cheapest conversion and the copy is ~free at
                # gossip-row sizes); past CTYPES_PTR_BYTES the raw data
                # pointer ships instead — above ~64 KiB the byte copy
                # dwarfs the ~µs pointer-extraction cost it was avoiding.
                arg, nbytes, keepalive = _ctypes_payload(tensor)
                rc = self._tx_send(self._tx, hb, port, op, nb, src, dst,
                                   weight, p_weight, arg, nbytes, urgent,
                                   stripe)
                del keepalive  # native enqueue copied before returning
            if rc == 0:
                return
            if rc == -4:
                # Deterministic, path-independent rejection (same rule the
                # Python path enforces before enqueue): the receiver's
                # fixed name[128] field caps every route.
                raise ValueError(
                    "window transport: window name exceeds the receiver's "
                    f"128-byte name field (127 usable bytes): {name!r}")
            flightrec.dump_on_error(
                f"native enqueue to {host}:{port} failed (code {rc})")
            raise ConnectionError(
                f"win transport send to {host}:{port} failed "
                f"(native code {rc})")
        from bluefog_tpu.utils import telemetry
        if len(name.encode()) >= 128:
            # Deterministic, path-independent rejection: the receiver's
            # fixed name[128] field caps every route.  Without this check
            # a long window name would ship fine inside a multi-message
            # batch (u16 name_len) but fail natively (-4) whenever it
            # flushed as a singleton — a timing-dependent error.
            raise ValueError(
                f"window transport: name exceeds 127 bytes: {name!r}")
        payload = np.ascontiguousarray(tensor).view(np.uint8).reshape(-1)
        # Guard BEFORE building labels: the disabled path must not pay the
        # per-message f-string/op-name allocations on the gossip hot path.
        if telemetry.enabled():
            telemetry.inc("bf_win_tx_msgs_total", op=_op_label(op))
            telemetry.inc("bf_win_tx_bytes_total", float(payload.size),
                          peer=f"{host}:{port}")
        if not self.coalesce:
            t0 = telemetry.start_timer()
            self._native_send(host, port, op, name, src, dst, weight,
                              p_weight, payload)
            if t0 is not None:
                # Per-message RPC latency: serialize + connect/enqueue on
                # the native client (TCP backpressure shows up here as
                # tail mass).  Guarded so the disabled path skips the
                # per-message label build too.
                telemetry.observe_since(t0, "bf_win_rpc_seconds",
                                        op=_op_label(op))
            return
        # Coalesced path: own a copy (the caller may free/reuse the array
        # the moment we return) and enqueue; the peer's worker ships it.
        from bluefog_tpu.ops import xlaffi
        xlaffi.count_host_copy(payload.size, "enqueue")
        msg: Msg = (op, name, src, dst, float(weight), float(p_weight),
                    payload.tobytes())
        self._sender(host, port, stripe).enqueue(
            msg, urgent=(op & ~OP_FLAG_MASK) in _URGENT_OPS)

    def kick(self) -> None:
        """Non-blocking flush request: wake every per-peer sender with a
        pending queue so it ships without waiting out the linger.  Used by
        overlap-mode optimizers to pace gossip onto the wire while the
        caller goes back to compute."""
        if self._tx is not None:
            self._lib.bf_wintx_kick(self._tx)
            return
        with self._senders_lock:
            senders = list(self._senders.values())
        for s in senders:
            with s.cond:
                if s.q:
                    s.flush_now = True
                    s.cond.notify_all()

    def set_partition(self, addrs) -> None:
        """Declare a set of ``(host, port)`` peers unreachable (chaos fault
        injection): every subsequent send to them fails like a dead link —
        immediately, with no native call and no retries.  ``None`` or an
        empty set heals the partition.  The error-epoch tokens scope the
        failures to ops that addressed the partitioned peers, exactly as
        with a real outage."""
        self._partitioned = frozenset(addrs or ())
        if self._tx is not None:
            csv = ",".join(f"{h}:{p}" for h, p in sorted(self._partitioned))
            self._lib.bf_wintx_set_partition(self._tx, csv.encode())

    def set_linger_ms(self, ms: float) -> None:
        """Runtime adaptation of the coalesce linger (the tuner's
        ``coalesce_linger_ms`` knob).  The Python sender workers read
        ``self._linger`` per flush wait, so the change is live on the
        Python hot path; the native tx loop bakes its linger at
        ``bf_wintx_start`` — a running native transport keeps its value
        (best-effort via ``bf_wintx_set_linger`` when the core grows one)
        and the new value applies from the next transport construction."""
        self._linger = max(0.0, float(ms)) / 1e3
        if self._tx:
            try:
                self._lib.bf_wintx_set_linger(
                    self._tx, int(self._linger * 1e6))
            except AttributeError:
                pass

    def set_send_delay(self, seconds: float) -> None:
        """Chaos link-delay fault: sleep ``seconds`` before every DATA
        enqueue (control ops never delayed), so the link observatory
        measures it as real per-edge one-way delay.  0.0 heals the
        fault and restores the undelayed send path."""
        self._send_delay = max(0.0, float(seconds))

    def drop_peer(self, host: str, port: int) -> None:
        """Retire EVERY stripe of a peer's sender cleanly (churn
        controller: the peer is dead by consensus).  Queued messages to it
        are discarded — there is no one left to receive them — producers
        blocked in any stripe's backpressure wait are released with a
        ConnectionError, and every per-stripe queue-depth gauge is
        cleared: a dead peer must never leave N-1 orphan stripe workers
        retrying into closed sockets or stale gauge series behind.
        Idempotent; a later send to the same address would lazily create
        fresh stripe senders (peer restart)."""
        from bluefog_tpu.utils import linkobs, telemetry
        # Same orphan-series hygiene for the link observatory: the dead
        # peer's goodput/retry-rate gauges are claims about a live wire.
        linkobs.clear_peer(f"{host}:{port}")
        if self._tx is not None:
            # Same retirement on the native queues (churn supervisor
            # follow-up): every stripe's C++ worker exits instead of
            # retrying into a closed socket; discarded messages keep
            # their counter (summed over stripes in C).
            dropped = int(self._lib.bf_wintx_drop_peer(
                self._tx, host.encode(), port))
            # Prune the stats-pump bookkeeping so a long churny job never
            # accumulates per-flush FFI calls and dead gauge series for
            # endpoints that no longer exist (re-added lazily on a fresh
            # send, exactly like the native peer itself).
            self._peer_addrs.discard((host, port))
            self._peer_last.pop((host, port), None)
            for k in [k for k in self._stripe_last if k[:2] == (host, port)]:
                # Same hygiene as _peer_last: a restarted peer's fresh
                # stripe counters restart at 0, and a stale baseline
                # would clamp its bf_win_tx_stripe_bytes_total diffs to
                # 0 until the new totals pass the old ones.
                self._stripe_last.pop(k, None)
            for k in range(self.n_stripes):
                telemetry.clear_gauge("bf_win_tx_queue_depth",
                                      peer=f"{host}:{port}", stripe=str(k))
            if dropped and telemetry.enabled():
                telemetry.inc("bf_win_tx_dropped_msgs_total", float(dropped),
                              peer=f"{host}:{port}")
            return
        with self._senders_lock:
            senders = [self._senders.pop(k)
                       for k in [k for k in self._senders
                                 if k[:2] == (host, port)]]
        dropped = 0
        for s in senders:
            with s.cond:
                n = len(s.q)
                dropped += n
                s.q.clear()
                s.bytes_pending = 0
                # Account the discarded messages as done-with-error so a
                # producer already blocked in flush() fails IMMEDIATELY
                # (error checked before seq_done) instead of waiting out
                # the closing grace for messages that can never be handed
                # to TCP.
                s.seq_done = s.seq_enq
                if n:
                    s.error = ConnectionError(
                        f"win transport peer {s.peer} retired by the churn "
                        f"controller with {n} queued message(s) discarded")
                    s.err_count += 1
                s.closing = True
                s.cond.notify_all()
            telemetry.clear_gauge("bf_win_tx_queue_depth", peer=s.peer,
                                  stripe=str(s.stripe))
        # No join: a worker stuck in a connect to a blackholed host exits
        # on its own when the native call returns (daemon thread, closing
        # set) — recovery must not pay that timeout.
        if dropped and telemetry.enabled():
            telemetry.inc("bf_win_tx_dropped_msgs_total", float(dropped),
                          peer=f"{host}:{port}")

    def error_token(self, addrs=None) -> int:
        """Snapshot for ``flush(since=...)``: take it BEFORE sending (for
        the same ``addrs``), and the flush raises if any batch to those
        peers failed in between — even one whose stored error a concurrent
        flusher already consumed.  Scoped per peer: failures on peers
        outside ``addrs`` never count."""
        if self._tx is not None:
            if addrs is None:
                return int(self._lib.bf_wintx_err_count(self._tx, None, 0))
            return sum(int(self._lib.bf_wintx_err_count(
                self._tx, h.encode(), p)) for h, p in addrs)
        return sum(s.err_count for s in self._select_senders(addrs))

    def _select_senders(self, addrs) -> List[_PeerSender]:
        """Senders for the given ``(host, port)`` addresses — EVERY stripe
        of each address (flush/error scoping is per peer, never per
        stripe: an op's edges may have sharded onto any of them)."""
        with self._senders_lock:
            if addrs is None:
                return list(self._senders.values())
            want = set(addrs)
            return [s for k, s in self._senders.items() if k[:2] in want]

    def flush(self, timeout: float = 300.0, addrs=None,
              since: Optional[int] = None) -> None:
        """Drain per-peer queues to the native send and surface any
        asynchronous send error.  Window ops call this at op boundaries so
        op completion keeps its legacy meaning (payload handed to TCP).

        ``addrs`` (iterable of ``(host, port)``) restricts the drain to
        the peers an op actually addressed — a dead or backpressuring
        neighbor must only stall ops that target it, exactly like the
        legacy blocking send.  ``since`` is an :meth:`error_token`
        snapshot taken over the SAME ``addrs``: any batch failure to
        those peers after it raises here, even when the per-sender error
        was already consumed by a concurrent flusher.  No-op on the
        legacy per-message path and on empty queues."""
        if self._tx is not None:
            self._flush_native(timeout, addrs, since)
            return
        senders = self._select_senders(addrs)
        errors = []
        for s in senders:
            try:
                s.flush(timeout)
            except Exception as e:  # noqa: BLE001 — all peers must drain
                errors.append(e)
        if errors:
            raise errors[0]
        if since is not None and \
                sum(s.err_count for s in senders) > since:
            raise ConnectionError(
                "win transport: a batched send containing this op's "
                "message(s) failed on a sender worker (see the "
                "bluefog_tpu log for the peer and cause)")

    def _flush_native(self, timeout: float, addrs, since) -> None:
        """Native-path flush: drain the C++ per-peer queues, surface stored
        async send errors, pump the native counters into telemetry, then
        apply the same error-epoch ``since`` rule as the Python path."""
        errors = []
        if addrs is None:
            rc = int(self._lib.bf_wintx_flush(self._tx, None, 0,
                                              float(timeout)))
            if rc:
                errors.append(rc)
        else:
            for (h, p) in addrs:
                rc = int(self._lib.bf_wintx_flush(self._tx, h.encode(), p,
                                                  float(timeout)))
                if rc:
                    errors.append(rc)
        self._pump_native_tx_stats()
        if errors:
            flightrec.dump_on_error(
                f"native flush failed (code {errors[0]})")
            rc = errors[0]
            if rc == -6:
                raise ConnectionError(
                    f"win transport flush timed out after {timeout:.0f}s "
                    "(messages still queued on the native sender)")
            if rc == -5:
                raise ConnectionError(
                    "win transport stopped with message(s) unsent")
            if rc == -8:
                raise ConnectionError(
                    "win transport peer retired by the churn controller "
                    "with queued message(s) discarded")
            raise ConnectionError(
                "win transport: a batched send containing this op's "
                f"message(s) failed on a native sender worker (code {rc})")
        if since is not None and self.error_token(addrs) > since:
            raise ConnectionError(
                "win transport: a batched send containing this op's "
                "message(s) failed on a sender worker (see the "
                "bluefog_tpu log for the peer and cause)")

    def _pump_native_tx_stats(self, tx=None, force: bool = False) -> None:
        """Diff the cumulative native sender counters into the telemetry
        registry — the SAME series the Python path maintains per message,
        observed from the native counters at flush boundaries instead
        (plus the ``bf_win_native_*`` markers).  Histogram buckets merge
        directly: the C++ core uses the shared boundary table.

        Rate-limited (≥50 ms between pumps unless ``force``): every
        window op flushes at its boundary, and ~1 ctypes stats call per
        peer per op would cost a meaningful slice of the zero-copy
        dispatch budget for series that only need scrape-rate freshness.
        ``stop()`` forces a final pump so nothing is lost."""
        from bluefog_tpu.utils import linkobs, telemetry
        tx = self._tx if tx is None else tx
        if tx is None or not telemetry.enabled():
            return
        now = time.monotonic()
        if not force and now - self._tx_pump_last < 0.05:
            return
        self._tx_pump_last = now
        with self._stats_lock:
            cur = native.WinTxStats()
            self._lib.bf_wintx_stats(tx, None, 0, ctypes.byref(cur))
            last, self._tx_last = self._tx_last, cur
            for i in range(16):
                d = cur.by_op[i] - last.by_op[i]
                if d > 0:
                    telemetry.inc("bf_win_tx_msgs_total", float(d),
                                  op=_op_label(i))
            d = cur.frames - last.frames
            if d > 0:
                telemetry.inc("bf_win_native_tx_frames_total", float(d))
            d = cur.batches - last.batches
            if d > 0:
                telemetry.inc("bf_win_tx_batches_total", float(d))
            d = cur.batched_msgs - last.batched_msgs
            if d > 0:
                telemetry.inc("bf_win_tx_batched_msgs_total", float(d))
            if cur.frames > 0:
                telemetry.set_gauge("bf_win_tx_coalesce_ratio",
                                    cur.batch_size_sum / cur.frames)
            telemetry.observe_bucket_counts(
                "bf_win_tx_batch_size",
                [cur.batch_size_hist[i] - last.batch_size_hist[i]
                 for i in range(25)],
                cur.batch_size_sum - last.batch_size_sum)
            telemetry.observe_bucket_counts(
                "bf_win_rpc_seconds",
                [cur.send_sec_hist[i] - last.send_sec_hist[i]
                 for i in range(25)],
                cur.send_sec_sum - last.send_sec_sum, op="native")
            # Per-peer series (bytes, errors, retries) + per-STRIPE series
            # (stripe bytes, stripe queue depth — an imbalanced (window,
            # row) shard shows up as one hot stripe here).
            for (h, p) in list(self._peer_addrs):
                ps = native.WinTxStats()
                self._lib.bf_wintx_stats(tx, h.encode(), p,
                                         ctypes.byref(ps))
                peer = f"{h}:{p}"
                lb, le, lr = self._peer_last.get((h, p), (0, 0, 0))
                # max(0, ...): a drop_peer/recreate cycle resets the
                # per-peer counters; the clamped diff keeps the labeled
                # series monotonic (aggregate series use the graveyard-
                # inclusive totals above and never reset).
                d = max(0, ps.bytes - lb)
                if d:
                    telemetry.inc("bf_win_tx_bytes_total", float(d),
                                  peer=peer)
                d = max(0, ps.errors - le)
                if d:
                    telemetry.inc("bf_win_tx_errors_total", float(d),
                                  peer=peer)
                d = max(0, ps.retries - lr)
                if d:
                    telemetry.inc("bf_win_tx_retries_total", float(d),
                                  peer=peer)
                self._peer_last[(h, p)] = (ps.bytes, ps.errors, ps.retries)
                for k in range(self.n_stripes):
                    ss = native.WinTxStats()
                    self._lib.bf_wintx_stripe_stats(tx, h.encode(), p, k,
                                                    ctypes.byref(ss))
                    lsb = self._stripe_last.get((h, p, k), 0)
                    d = max(0, ss.bytes - lsb)
                    if d:
                        telemetry.inc("bf_win_tx_stripe_bytes_total",
                                      float(d), peer=peer, stripe=str(k))
                        # Same diff feeds the link observatory's goodput
                        # estimator — the pump's flush-boundary cadence
                        # is exactly its windowing granularity.
                        linkobs.note_tx(peer, k, float(d))
                    telemetry.set_gauge("bf_win_tx_queue_depth",
                                        float(ss.queue_len), peer=peer,
                                        stripe=str(k))
                    self._stripe_last[(h, p, k)] = ss.bytes

    def _pump_native_rx_stats(self) -> None:
        """Diff the cumulative native drain counters into telemetry (same
        series the Python decode path maintains per frame/message)."""
        from bluefog_tpu.utils import telemetry
        if not telemetry.enabled():
            return
        cur = native.WinRxStats()
        self._lib.bf_winsvc_rx_stats(self._svc, ctypes.byref(cur))
        last, self._rx_last = self._rx_last, cur
        d = cur.batch_frames - last.batch_frames
        if d > 0:
            telemetry.inc("bf_win_rx_batches_total", float(d))
            telemetry.inc("bf_win_native_rx_frames_total", float(d))
        d = cur.bytes - last.bytes
        if d > 0:
            telemetry.inc("bf_win_rx_bytes_total", float(d))
        for i in range(16):
            d = cur.by_op[i] - last.by_op[i]
            if d > 0:
                telemetry.inc("bf_win_rx_msgs_total", float(d),
                              op=_op_label(i))
        d = cur.folded_msgs - last.folded_msgs
        if d > 0:
            telemetry.inc("bf_win_native_rx_folded_msgs_total", float(d))
        d = cur.commits - last.commits
        if d > 0:
            telemetry.inc("bf_win_native_rx_commits_total", float(d))
        if self.decode_threads > 0:
            # Decode-pool utilization: workers busy at snapshot time.
            # Pinned at the pool size means inbound decode is the
            # bottleneck — raise BLUEFOG_TPU_WIN_DECODE_THREADS.
            telemetry.set_gauge("bf_win_rx_decode_pool_busy",
                                float(cur.decode_busy))
        telemetry.observe_bucket_counts(
            "bf_win_rx_batch_size",
            [cur.batch_size_hist[i] - last.batch_size_hist[i]
             for i in range(25)],
            cur.batch_size_sum - last.batch_size_sum)

    def _sender(self, host: str, port: int, stripe: int = 0) -> _PeerSender:
        key = (host, port, stripe)
        with self._senders_lock:
            s = self._senders.get(key)
            if s is None:
                s = self._senders[key] = _PeerSender(self, host, port,
                                                     stripe)
            return s

    def _send_frames(self, host: str, port: int, batch: List[Msg],
                     stripe: int = 0) -> None:
        """Worker-side: ship a drained queue as ONE native send (an
        OP_BATCH frame), or as the plain single frame when only one message
        coalesced (no container overhead, bit-identical legacy wire)."""
        from bluefog_tpu.utils import linkobs, telemetry
        if telemetry.enabled():
            telemetry.inc("bf_win_tx_stripe_bytes_total",
                          float(sum(len(m[6]) for m in batch)),
                          peer=f"{host}:{port}", stripe=str(stripe))
        linkobs.note_tx(f"{host}:{port}", stripe,
                        float(sum(len(m[6]) for m in batch)))
        frame_op = batch[0][0] if len(batch) == 1 else OP_BATCH
        if flightrec.enabled():
            flightrec.note(flightrec.FLUSH, op=frame_op, stripe=stripe,
                           src=-1, dst=port, seq=len(batch),
                           length=sum(len(m[6]) for m in batch),
                           name=f"{host}:{port}")
        if len(batch) == 1:
            op, name, src, dst, weight, p_weight, payload = batch[0]
            blob = np.frombuffer(payload, np.uint8)
            t0 = telemetry.start_timer()
            self._native_send(host, port, op, name, src, dst, weight,
                              p_weight, blob)
            if t0 is not None:
                telemetry.observe_since(t0, "bf_win_rpc_seconds",
                                        op=_op_label(op))
        else:
            blob = np.frombuffer(_encode_batch(batch), np.uint8)
            t0 = telemetry.start_timer()
            self._native_send(host, port, OP_BATCH, "", -1, -1, 0.0, 0.0,
                              blob)
            if t0 is not None:
                telemetry.observe_since(t0, "bf_win_rpc_seconds",
                                        op="batch")
        if flightrec.enabled():
            # src carries the rc convention of the native recorder: this
            # site only runs on success (a failed send raised above).
            flightrec.note(flightrec.SENDMSG, op=frame_op, stripe=stripe,
                           src=0, dst=port, seq=len(batch),
                           length=blob.size, name=f"{host}:{port}")
        with self._stats_lock:  # several sender threads update the ratio
            self._tx_frames += 1
            self._tx_msgs += len(batch)
            ratio = self._tx_msgs / self._tx_frames
        if telemetry.enabled():
            telemetry.observe("bf_win_tx_batch_size", float(len(batch)))
            if len(batch) > 1:
                telemetry.inc("bf_win_tx_batches_total")
                telemetry.inc("bf_win_tx_batched_msgs_total",
                              float(len(batch)))
            telemetry.set_gauge("bf_win_tx_coalesce_ratio", ratio)

    def _native_send(self, host: str, port: int, op: int, name: str,
                     src: int, dst: int, weight: float, p_weight: float,
                     payload: np.ndarray) -> None:
        """One native RPC, with up to ``BLUEFOG_TPU_WIN_RETRIES`` jittered
        exponential-backoff retries on transient failure (a peer restarting
        between the pooled connection's own stale-fd retry and now) before
        raising ConnectionError.  Each retry attempt is counted in
        ``bf_win_tx_retries_total``."""
        from bluefog_tpu.utils import telemetry
        if (host, port) in self._partitioned:
            # Chaos partition (utils/chaos.py): this link is declared down;
            # fail exactly like an unreachable peer, with no native call and
            # no retries (a partition does not heal on a 50 ms backoff).
            if telemetry.enabled():
                telemetry.inc("bf_win_tx_errors_total",
                              peer=f"{host}:{port}")
            raise ConnectionError(
                f"win transport send to {host}:{port} dropped "
                "(chaos partition)")
        args = (host.encode(), port, op, name.encode(), src, dst,
                float(weight), float(p_weight),
                payload.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                payload.size)
        rc = self._lib.bf_winsvc_send(*args)
        # Retry only transient failures (connect/write to a restarting
        # peer); -1 (address resolution, the directory carries numeric
        # IPs) and -4 (name too long) are deterministic.
        attempt = 0
        while rc not in (0, -1, -4) and attempt < self._retries:
            telemetry.inc("bf_win_tx_retries_total",
                          peer=f"{host}:{port}")
            # Full jitter on an exponential ladder: a gang-wide blip must
            # not make every peer's sender hammer the restarting host in
            # lockstep at exactly base, 2*base, 4*base...
            import random
            time.sleep(self._retry_backoff * (2 ** attempt)
                       * (0.5 + random.random()))
            attempt += 1
            rc = self._lib.bf_winsvc_send(*args)
        if rc != 0:
            if telemetry.enabled():
                telemetry.inc("bf_win_tx_errors_total",
                              peer=f"{host}:{port}")
            if rc != -4:
                flightrec.dump_on_error(
                    f"send to {host}:{port} failed (code {rc})")
            if rc == -4:
                # Deterministic caller bug, not a connectivity problem:
                # the receiver's fixed name[128] field rejects the route.
                raise ValueError(
                    "window transport: window name exceeds the receiver's "
                    f"128-byte name field (127 usable bytes): {name!r}")
            raise ConnectionError(
                f"win transport send to {host}:{port} failed (code {rc})")

    # -- inbound -----------------------------------------------------------
    def _drain(self):
        if self.native_path:
            return self._drain_native()
        return self._drain_python()

    def _drain_native(self):
        """Native drain loop: ``bf_winsvc_drain`` pops queued frames and
        hands back an ordered item list — batch decode, payload codecs and
        same-slot folding already done in C++.  Per-item Python work is
        per RUN (one folded commit per slot run), not per message; raw
        items (control ops, unregistered windows, foreign frames) flow
        through the exact legacy paths."""
        from bluefog_tpu.utils import telemetry
        lib, svc = self._lib, self._svc
        burst = 0          # messages applied back-to-back (depth proxy)
        burst_t0 = 0.0
        burst_t_end = 0.0  # after the LAST applied result — the blocking
                           # idle wait inside the drain call is not burst
                           # service time
        max_frames = 64
        # Block INSIDE the native call (GIL released) while the queue is
        # empty — no Python-side poll loop stealing the GIL from senders.
        # Wake-on-data is instant (condition variable), so the 50 ms cap
        # only bounds how often the stop flag is checked.
        wait_ms = 50
        while not self._stop.is_set():
            t_call = time.perf_counter()
            n = lib.bf_winsvc_drain(
                svc, self._items, self._items_cap,
                self._raw_buf.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint8)),
                self._raw_buf.size,
                self._val_buf.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_float)),
                self._val_buf.size, max_frames, wait_ms)
            if n > 0 and burst \
                    and time.perf_counter() - t_call > 0.002:
                # The call sat WAITING before this data arrived: the queue
                # had run dry, so the previous burst ended back then —
                # same boundary the polling Python drain observes.
                telemetry.set_gauge("bf_win_rx_queue_depth", burst)
                telemetry.observe("bf_win_drain_burst_seconds",
                                  burst_t_end - burst_t0)
                burst = 0
                self._pump_native_rx_stats()
            if n == -1:  # next frame's raw payloads exceed the buffer
                self._raw_buf = np.empty(max(self._raw_buf.size * 2, 1 << 24),
                                         dtype=np.uint8)
                continue
            if n == -2:  # next frame's folded values exceed the buffer
                self._val_buf = np.empty(max(self._val_buf.size * 2, 1 << 22),
                                         dtype=np.float32)
                continue
            if n == -3:  # more sub-message runs than item slots
                self._items_cap *= 2
                self._items = (native.WinItem * self._items_cap)()
                continue
            if n == 0:
                # The wait already happened inside the native call — no
                # Python-side sleep here.
                if burst:
                    telemetry.set_gauge("bf_win_rx_queue_depth", burst)
                    telemetry.observe("bf_win_drain_burst_seconds",
                                      burst_t_end - burst_t0)
                    burst = 0
                    self._pump_native_rx_stats()
                continue
            if not burst:
                burst_t0 = time.perf_counter()
            burst += self._apply_native_items(int(n))
            burst_t_end = time.perf_counter()

    def _raw_item_msg(self, it, raw_mv) -> Msg:
        return (int(it.op), it.name.decode(), int(it.src), int(it.dst),
                float(it.weight), float(it.p_weight),
                raw_mv[it.off:it.off + it.len])

    def _fallback_batch_frame(self, payload) -> Optional[List[Msg]]:
        """Python-decode a batch frame the native drain handed back whole
        (bad version, oversized names): the Python decoder owns the error
        reporting AND the telemetry for these, exactly as on the fallback
        path.  Returns None when the frame is undecodable (logged)."""
        from bluefog_tpu.utils import telemetry
        try:
            sub = _decode_batch(payload)
        except Exception:  # noqa: BLE001 — drain must survive
            import logging
            logging.getLogger("bluefog_tpu").exception(
                "window transport batch decode failed")
            return None
        if telemetry.enabled():
            telemetry.inc("bf_win_rx_batches_total")
            telemetry.inc("bf_win_rx_bytes_total", float(len(payload)))
            telemetry.observe("bf_win_rx_batch_size", float(len(sub)))
            for m in sub:
                telemetry.inc("bf_win_rx_msgs_total", op=_op_label(m[0]))
        return sub

    def _apply_native_items(self, n: int) -> int:
        """Apply one native drain result in order; returns the number of
        wire messages it carried.  No per-message telemetry here: natively
        decoded frames are tallied in the C++ counters pumped by
        :meth:`_pump_native_rx_stats` (fallback whole frames excepted —
        their Python decode owns the counting)."""
        raw_mv = memoryview(self._raw_buf)
        if self._apply_items is not None:
            items = []
            msgs = 0
            for i in range(n):
                it = self._items[i]
                if it.kind:
                    vals = np.frombuffer(self._val_buf, np.float32,
                                         count=it.len, offset=it.off * 4)
                    # Trace tag of the last tagged message folded into
                    # this entry (None untagged) — same (src, seq, mono,
                    # unix, step) shape trace_strip returns on the
                    # Python path.
                    trace = (int(it.trace_src), int(it.trace_seq),
                             int(it.trace_mono_us),
                             int(it.trace_unix_us),
                             int(it.trace_step)) \
                        if it.trace_seq else None
                    items.append((1, (it.name.decode(), bool(it.replace),
                                      int(it.src), int(it.dst),
                                      float(it.p_weight), int(it.puts),
                                      int(it.accs), vals,
                                      int(it.wire_bytes), trace)))
                    msgs += it.puts + it.accs
                    continue
                if int(it.op) == OP_BATCH:
                    sub = self._fallback_batch_frame(
                        raw_mv[it.off:it.off + it.len])
                    if sub is not None:
                        # Splice in place: stream order vs surrounding
                        # items is exactly arrival order.
                        items.extend((0, m) for m in sub)
                        msgs += len(sub)
                    continue
                items.append((0, self._raw_item_msg(it, raw_mv)))
                msgs += 1
            try:
                self._apply_items(items)
            except Exception:  # noqa: BLE001 — drain thread must survive
                import logging
                logging.getLogger("bluefog_tpu").exception(
                    "window transport apply failed")
            return msgs
        # Legacy-callback consumer (no apply_items): regroup raw items by
        # their frame tag so each decoded OP_BATCH frame is delivered as
        # ONE apply_batch call — the PR-4 contract, preserved for
        # consumers that only supply apply/apply_batch.  Commits cannot
        # occur here (nothing registered windows), but are drop-logged
        # defensively.
        import logging
        msgs = 0
        i = 0
        while i < n:
            it = self._items[i]
            if it.kind:
                logging.getLogger("bluefog_tpu").warning(
                    "window transport: folded commit for %r dropped (no "
                    "apply_items consumer)", it.name.decode())
                i += 1
                continue
            if int(it.op) == OP_BATCH:
                sub = self._fallback_batch_frame(
                    raw_mv[it.off:it.off + it.len])
                i += 1
                if sub is None:
                    continue
                msgs += len(sub)
                group = sub
            elif it.frame:
                group = []
                f = it.frame
                while (i < n and self._items[i].kind == 0
                       and self._items[i].frame == f):
                    group.append(self._raw_item_msg(self._items[i], raw_mv))
                    i += 1
                msgs += len(group)
            else:
                group = None  # singleton: per-message apply
                msg = self._raw_item_msg(it, raw_mv)
                i += 1
                msgs += 1
            try:
                if group is None:
                    self._apply(*msg)
                elif self._apply_batch is not None:
                    self._apply_batch(group)
                else:
                    for m in group:
                        self._apply(*m)
            except Exception:  # noqa: BLE001 — drain thread must survive
                logging.getLogger("bluefog_tpu").exception(
                    "window transport apply failed")
        return msgs

    def _drain_python(self):
        from bluefog_tpu.utils import telemetry
        msg = native.WinMsg()
        burst = 0  # consecutive non-empty recvs: inbound-queue depth proxy
        burst_t0 = 0.0
        while not self._stop.is_set():
            got = self._lib.bf_winsvc_recv(
                self._svc, ctypes.byref(msg),
                self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                self._buf.size)
            if got == -1:  # payload larger than buffer: grow and retry
                self._buf = np.empty(max(self._buf.size * 2, 1 << 24),
                                     dtype=np.uint8)
                continue
            if got == 0:
                if burst:
                    # The native layer exposes no queue-length API, so the
                    # burst length — messages drained back-to-back before
                    # the queue ran dry — is the depth proxy.
                    telemetry.set_gauge("bf_win_rx_queue_depth", burst)
                    # Burst service time: how long the drain thread spent
                    # applying back-to-back messages before the queue ran
                    # dry — tail mass here means inbound gossip arrives
                    # faster than this host applies it.
                    telemetry.observe("bf_win_drain_burst_seconds",
                                      time.perf_counter() - burst_t0)
                    burst = 0
                self._stop.wait(self._interval)
                continue
            if not burst:
                burst_t0 = time.perf_counter()
            burst += 1
            # Zero-copy view into the recv buffer: apply copies what it
            # keeps (the arithmetic it performs materializes fresh arrays
            # anyway; only parked/deferred messages need an explicit copy).
            payload = memoryview(self._buf)[:msg.payload_len]
            op = int(msg.op)
            try:
                if op == OP_BATCH:
                    self._dispatch_batch(payload)
                else:
                    if telemetry.enabled():  # skip label render when off
                        telemetry.inc("bf_win_rx_msgs_total",
                                      op=_op_label(op))
                        telemetry.inc("bf_win_rx_bytes_total",
                                      float(msg.payload_len))
                    self._apply(op, msg.name.decode(), int(msg.src),
                                int(msg.dst), float(msg.weight),
                                float(msg.p_weight), payload)
            except Exception:  # noqa: BLE001 — drain thread must survive
                import logging
                logging.getLogger("bluefog_tpu").exception(
                    "window transport apply failed")

    def _dispatch_batch(self, payload: memoryview) -> None:
        from bluefog_tpu.utils import telemetry
        msgs = _decode_batch(payload)
        if telemetry.enabled():
            telemetry.inc("bf_win_rx_batches_total")
            telemetry.inc("bf_win_rx_bytes_total", float(len(payload)))
            telemetry.observe("bf_win_rx_batch_size", float(len(msgs)))
            for m in msgs:
                telemetry.inc("bf_win_rx_msgs_total", op=_op_label(m[0]))
        if self._apply_batch is not None:
            self._apply_batch(msgs)
        else:
            for m in msgs:
                self._apply(*m)

    def stop(self):
        # Unpublish the native sender handle FIRST: concurrent senders
        # (heartbeat thread, overlapped puts, the XLA plan dispatch) gate
        # on `self._tx`; nulling it before bf_wintx_stop frees the
        # struct shrinks the use-after-free window to callers already
        # past the read (whom the C++ inflight guard + stopping flag
        # then handle).
        tx, self._tx = self._tx, None
        if tx is not None:
            try:
                self._pump_native_tx_stats(tx, force=True)
            except Exception:  # noqa: BLE001 — telemetry must not block stop
                pass
            self._lib.bf_wintx_stop(tx)
        with self._senders_lock:
            senders = list(self._senders.values())
            self._senders.clear()
        for s in senders:
            s.stop()
        self._stop.set()
        self._drainer.join(timeout=5)
        if self._svc:
            if self.native_path:
                try:
                    self._pump_native_rx_stats()
                except Exception:  # noqa: BLE001
                    pass
            self._lib.bf_winsvc_stop(self._svc)
            self._svc = None
