"""Whole-step compilation: the gossip training step as one XLA program.

The eager window-optimizer step crosses the Python boundary four times
per iteration — grad/update math, per-bucket host put dispatch, drain,
parameter rebuild — so the neighbor averaging BlueFog promises to hide
inside compute never actually hides end-to-end.  This module is the
compiler pass that closes the boundary: it lowers (optimizer update ×
the schedule layer's ``window_plan()`` × codec × per-bucket window put)
into a single jitted program, behind ``BLUEFOG_TPU_FUSED_STEP`` (default
OFF — ``=0`` pins the eager path as the bitwise oracle).

Program shape (built once per cache key, replayed every step):

  * **step program** — the vmapped base-optimizer update, the per-bucket
    flat concatenation, and one donated-buffer FFI put
    (``xlaffi.xla_put_program_pass``, native ``bf_xla_win_put_pass``)
    per fusion bucket.  The put is a *passthrough*: its first output IS
    the bucket flat (``input_output_aliases`` donation), so downstream
    consumers data-depend on the put — XLA issues each bucket's put
    exactly when that bucket's bytes materialize, pipelining the sends
    against the remaining update math by data dependence instead of the
    hand-rolled ``_pending`` handle list the eager overlap mode keeps.
  * **finish program** — the drain: ``win_update`` (or the push-sum
    ``win_update_then_collect``) runs host-side once the put statuses
    have landed, handing its fresh combine buffers (``commit=False``)
    straight to one jitted program doing the per-leaf rebuild
    (split/reshape/cast) and the owned-row merge; the jit argument path
    is where the host arrays re-enter jax — one batched conversion,
    measured ~5x cheaper than per-array ``commit_to_jax`` re-entry.
    (Embedding the drain as an ordered ``io_callback`` inside the
    program was measured ~1.5x slower end to end: the callback
    machinery's device round-trip dwarfs the fold it wraps, and the
    put-status block already gives the same ordering for free.)

Between the two programs the host performs exactly what ``_do_put`` does
around the native plan dispatch and an in-program custom call cannot:
local-edge staging writes, the scoped transport flush, the post-send
self-publish (push-sum mass conservation) and the periodic push-sum
fence — see ``window._fused_host_finish``.

Cache + invalidation: programs are keyed on (family, tree structure,
leaf avals, window names, ``basics`` topology generation, committed
membership epoch, codec, associated-P arming, resolved edge weights,
mutex mode, transport handle).  ``set_topology`` bumps the topology
generation and a committed membership change bumps the epoch, so a stale
program can never dispatch against a new topology generation — the next
step misses the cache and rebuilds.

The schedule layer is a first-class input: the resolved edge weights
compile through ``ops.schedule.compile_static`` into a
``CompiledSchedule`` re-tagged ``lowering="fused"`` and the program's
per-source push lists are consumed from its ``window_plan()`` — the same
artifact ``tools schedule-dump --lowering fused`` previews without
running anything (:func:`modeled_overlap`).

Telemetry: ``bf_fused_step_active`` (gauge), ``bf_fused_step_compile_seconds``
(histogram, observed at build), ``bf_fused_step_puts_total`` (counter,
one per in-program plan dispatch) and ``bf_fused_step_overlap_seconds``
(histogram labeled by bucket: wall time between a bucket's put issuing
inside the program and the program completing — the window the put
actually overlapped).  With the flag off none of these mutate.

In-program probes (``BLUEFOG_TPU_PROBE``, default on): when the native
core exports ``bf_xla_probe``, the program threads passthrough timestamp
custom calls at its semantic seams — grad-ready at entry, pre/post each
bucket's put chain, step end — and the host notes its drain seams into
the same ring; ``utils/probes.reconcile`` then turns one post-step drain
into measured overlap (``bf_fused_overlap_ratio``), per-bucket issue
latencies, real ``bf_step_phase_seconds`` attribution for an active
``bf.step_profile()`` and chrome-timeline probe lanes.  The probes
supersede the Python ``io_callback`` stamps (kept as the fallback when
the ``.so`` predates the probe symbols).  ``BLUEFOG_TPU_PROBE=0``
compiles none of this — the program is bitwise the pre-probe lowering.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

import numpy as np

from bluefog_tpu import basics
from bluefog_tpu.ops import window as W
from bluefog_tpu.ops import xlaffi
from bluefog_tpu.utils import probes as _probes

__all__ = ["FusedStep", "FusedFallback", "modeled_overlap"]

# Bounded program cache per optimizer: topology flips A->B->A should hit,
# a topology sweep should not grow without bound.
_MAX_PROGRAMS = 4


class FusedFallback(Exception):
    """This step cannot take the fused path — run the eager oracle.

    Raised for *configuration* reasons (disarmed XLA path, unsupported
    layout, async mode), never mid-dispatch: by the time the fused
    program runs, every disqualifier has already been checked."""


class _Program:
    """One compiled fused step: the two jitted programs plus the host
    metadata needed to dispatch them."""

    __slots__ = (
        "key", "step_fn", "finish_fn", "finish_host_drain", "names",
        "plans", "tx", "edges", "remote_procs", "sched", "stamps",
        "n_put_calls", "accumulate", "probes", "shard_name",
    )


def _edge_token(dst_weights):
    """Hashable identity of a ``dst_weights`` argument for cache keying."""
    if dst_weights is None:
        return None
    if isinstance(dst_weights, dict):
        return tuple(sorted((k, float(v)) for k, v in dst_weights.items()))
    arr = np.asarray(dst_weights, dtype=float)
    return ("matrix", arr.shape, arr.tobytes())


def _self_weight_token(self_weight):
    if self_weight is None:
        return None
    arr = np.asarray(self_weight, dtype=float)
    return (arr.shape, arr.tobytes())


def compile_fused_schedule(edges: Dict[tuple, float], n: int):
    """Compile a resolved ``{(src, dst): w}`` edge set into a
    ``CompiledSchedule`` artifact tagged ``lowering="fused"`` — the
    schedule-layer representation the fused program consumes (via
    ``window_plan()``) and ``tools schedule-dump`` previews."""
    from bluefog_tpu.ops import schedule as S
    m = np.zeros((n, n), dtype=float)
    for (src, dst), w in edges.items():
        if src != dst:
            m[src, dst] = float(w)
    sched = S.compile_static(basics.load_topology(), src_weights=m)
    return S.as_compiled(sched, lowering="fused")


def modeled_overlap(bucket_bytes: List[int]) -> List[dict]:
    """Static overlap preview for ``k`` fusion buckets (no execution).

    Model: the update math costs one unit spread evenly over the buckets
    in order; bucket ``i``'s put issues the moment its flat materializes
    (fraction ``(i+1)/k`` of the compute) and its wire time then runs
    concurrently with the remaining ``(k-i-1)/k`` of compute — the data-
    dependence pipelining the fused program gets from XLA.  Returns one
    row per bucket: ``bytes``, ``ready_at`` (fraction of compute done
    when the put issues) and ``overlap`` (fraction of the compute the
    put's wire time can hide behind)."""
    k = len(bucket_bytes)
    rows = []
    for i, nb in enumerate(bucket_bytes):
        rows.append({
            "bucket": i,
            "bytes": int(nb),
            "ready_at": (i + 1) / k if k else 1.0,
            "overlap": (k - i - 1) / k if k else 0.0,
        })
    return rows


class FusedStep:
    """Per-optimizer fused-step compiler + dispatcher.

    Owned by a window optimizer (``optim/window_optimizers.py``); one
    instance caches up to ``_MAX_PROGRAMS`` compiled programs keyed by
    (tree structure, topology generation, membership epoch, edges,
    codec, ...) and replays them across steps."""

    def __init__(self, opt):
        self.opt = opt
        self._programs: "Dict[tuple, _Program]" = {}
        self.builds = 0          # program (re)builds — tests assert on this
        self.fused_steps = 0     # steps served by a fused program
        self._warned: set = set()

    # -- engagement --------------------------------------------------------

    def _fallback(self, reason: str):
        from bluefog_tpu.utils import telemetry
        telemetry.set_gauge("bf_fused_step_active", 0.0)
        if reason not in self._warned:
            self._warned.add(reason)
            from bluefog_tpu.utils.logging import get_logger
            get_logger().warning(
                "fused step: falling back to the eager path (%s); "
                "set BLUEFOG_TPU_FUSED_STEP=0 to silence", reason)
        raise FusedFallback(reason)

    def _check_eligible(self, params):
        import jax
        import jax.numpy as jnp
        opt = self.opt
        if not opt.fuse:
            self._fallback("fuse=False (per-leaf windows) is not lowered")
        if getattr(opt, "_shard_plan", None) is not None and not opt._buckets:
            # Every leaf is sharded: there is no replicated bucket window
            # to anchor the program's edge resolution or its put plans.
            self._fallback("sharded plan with no replicated leaves")
        if opt._async_on:
            self._fallback("async mode (BLUEFOG_TPU_ASYNC) keeps the "
                           "eager barrier-free step")
        leaves = jax.tree_util.tree_leaves(params)
        if not all(np.asarray(x).dtype == jnp.float32 for x in leaves):
            self._fallback("non-f32 parameter leaves")
        d = W._store.distrib
        if d is not None:
            if not xlaffi.armed():
                self._fallback("XLA put path disarmed: %s"
                               % (xlaffi.disarm_reason() or "unknown"))
            if not xlaffi.has_passthrough():
                self._fallback("native core lacks bf_xla_win_put_pass "
                               "(rebuild bluefog_tpu/native)")
            if getattr(d.transport, "_tx", None) is None:
                self._fallback("window transport is not native "
                               "(BLUEFOG_TPU_WIN_NATIVE=0?)")
        return d

    # -- program build -----------------------------------------------------

    def _key(self, family, treedef, avals, dst_weights, self_weight,
             require_mutex, d):
        from bluefog_tpu.utils import config, telemetry
        view = getattr(self.opt, "membership_change", None)
        cfg = config.get()
        plan_sh = getattr(self.opt, "_shard_plan", None)
        return (
            family, treedef, avals, tuple(self.opt._names),
            (None if plan_sh is None else plan_sh.signature),
            basics._ctx.topology_version,
            (view.epoch if view is not None else -1),
            _edge_token(dst_weights), _self_weight_token(self_weight),
            bool(require_mutex), cfg.win_compression,
            W._store.associated_p_enabled,
            (getattr(d.transport, "_tx", None) if d is not None else None),
            telemetry.enabled(),
            # Flipping BLUEFOG_TPU_PROBE (or a core rebuild gaining the
            # probe symbols) must miss the cache: the probe ops are
            # compiled INTO the program.
            (cfg.probe and _probes.available()),
        )

    def _resolve_edges(self, dst_weights):
        """The schedule-layer pass: resolve the caller's weights exactly
        as the eager put does, compile them into the ``lowering="fused"``
        artifact, and read the program's per-source push lists back off
        ``window_plan()``."""
        win = W._store.get(self.opt._names[0])
        resolved = W._resolve_edge_weights(dst_weights, win.out_nbrs, 1.0)
        sched = compile_fused_schedule(resolved, self.opt._n)
        plan = sched.window_plan()
        edges = {(src, dst): w
                 for src in range(self.opt._n)
                 for dst, w in plan[src]}
        return edges, sched

    def _build(self, family, params, grads, base_state, *, dst_weights,
               self_weight, require_mutex, d, key):
        import jax
        import jax.numpy as jnp
        from bluefog_tpu.utils import telemetry

        opt = self.opt
        accumulate = family == "pushsum"
        rows = opt._rows
        edges, sched = self._resolve_edges(dst_weights)
        owned_edges = {(s, t): w for (s, t), w in edges.items()
                       if W._owns(s)}
        remote_procs = ({d.rank_owner[t] for (s, t) in owned_edges
                         if not W._owns(t)} if d is not None else set())

        prog = _Program()
        prog.key = key
        # Under a shard plan the last window is the sharded slices'
        # in-group window: the compiled program covers the replicated
        # bucket windows only (its put-plan builder skips the sharded
        # slices at plan-compile time), and the sharded window rides the
        # host drain with its in-group weight overrides.
        plan_sh = getattr(opt, "_shard_plan", None)
        prog.names = list(opt._names[:-1] if plan_sh is not None
                          else opt._names)
        prog.shard_name = (opt._sharded_name if plan_sh is not None
                           else None)
        prog.edges = owned_edges
        prog.remote_procs = remote_procs
        prog.sched = sched
        prog.tx = getattr(d.transport, "_tx", None) if d is not None else None
        prog.accumulate = accumulate
        prog.stamps = [None] * len(prog.names)
        prog.plans = []
        op = W.OP_ACCUMULATE if accumulate else W.OP_PUT
        remote_edges = tuple(
            ((s, t), w) for (s, t), w in owned_edges.items()
            if not W._owns(t))
        for name in prog.names:
            if d is None or not remote_edges:
                prog.plans.append(None)
                continue
            win = W._store.get(name)
            plan = xlaffi.prepare_put(d, win, name, op, remote_edges,
                                      per_edge=False)
            if plan is None:
                self._fallback("native plan build failed for %r" % name)
            prog.plans.append(plan)
        prog.n_put_calls = sum(
            len(p.groups) for p in prog.plans if p is not None)

        # Passthrough put closures + per-bucket issue-time stamps.
        put_fns: List[List] = []
        for plan in prog.plans:
            fns = []
            if plan is not None:
                for pid, _grp in plan.groups:
                    f = xlaffi.xla_put_program_pass(pid, prog.tx)
                    if f is None:
                        self._fallback("jax FFI module unavailable for "
                                       "the in-program put")
                    fns.append(f)
            put_fns.append(fns)

        # In-program probes: passthrough timestamp custom calls threaded
        # at the program's seams via data dependence (operand aliased to
        # result — XLA cannot reorder them past their consumers).  When
        # they compile in, the Python io_callback stamps below are
        # superseded: the probe reconciler feeds the same histogram from
        # in-program clocks at a fraction of the cost.
        from bluefog_tpu.utils import config as _cfgmod
        k_buckets = len(opt._buckets)
        probe_on = _cfgmod.get().probe and xlaffi.has_probe() \
            and _probes.arm()
        p_grad = p_end = None
        p_pre: List[Optional[object]] = []
        p_post: List[Optional[object]] = []
        if probe_on:
            p_grad = xlaffi.xla_probe_program(_probes.GRAD_READY)
            p_end = xlaffi.xla_probe_program(_probes.STEP_END)
            p_pre = [xlaffi.xla_probe_program(_probes.BUCKET_PRE + i)
                     for i in range(k_buckets)]
            p_post = [xlaffi.xla_probe_program(_probes.BUCKET_POST + i)
                      for i in range(k_buckets)]
            probe_on = (p_grad is not None and p_end is not None
                        and all(p_pre) and all(p_post))
        prog.probes = probe_on

        stamp_fns: List[Optional[object]] = [None] * len(prog.names)
        if telemetry.enabled() and any(put_fns) and not probe_on:
            try:
                from jax.experimental import io_callback as _iocb
            except Exception:  # noqa: BLE001 — no stamps on older jax
                _iocb = None
            if _iocb is not None:
                def _mk_stamp(bi):
                    def _cb(_st):
                        prog.stamps[bi] = time.monotonic()
                        return np.int32(0)

                    def _emit(status):
                        return _iocb(_cb,
                                     jax.ShapeDtypeStruct((), jnp.int32),
                                     status, ordered=False)
                    return _emit
                stamp_fns = [_mk_stamp(i) for i in range(len(prog.names))]

        base = opt.base
        buckets = opt._buckets
        sh_idx = (tuple(opt._shard_leaf_idx) if plan_sh is not None
                  else ())

        def _step(params_t, grads_t, state_t):
            if probe_on:
                # Grad-ready: threaded through one gradient leaf, so the
                # stamp data-precedes the update math consuming it.
                g_leaves, g_td = jax.tree_util.tree_flatten(grads_t)
                g_leaves[0] = p_grad(g_leaves[0])
                grads_t = jax.tree_util.tree_unflatten(g_td, g_leaves)
            updates, new_state = jax.vmap(
                lambda g, s, p: base.update(g, s, p))(
                    grads_t, state_t, params_t)
            new_params = jax.tree.map(lambda p, u: p + u, params_t, updates)
            leaves = jax.tree_util.tree_leaves(new_params)
            flats, statuses = [], []
            for bi, idxs in enumerate(buckets):
                flat = jnp.concatenate(
                    [jnp.reshape(leaves[i], (rows, -1)) for i in idxs],
                    axis=1)
                if probe_on:
                    flat = p_pre[bi](flat)  # bucket flat materialized
                sts = []
                for f in put_fns[bi]:
                    flat, st = f(flat)
                    sts.append(st)
                if probe_on:
                    flat = p_post[bi](flat)  # put chain issued
                st_all = (jnp.concatenate(sts) if sts
                          else jnp.zeros((1,), jnp.int32))
                if sts and stamp_fns[bi] is not None:
                    stamp_fns[bi](st_all)
                flats.append(flat)
                statuses.append(st_all)
            if probe_on and flats:
                flats[-1] = p_end(flats[-1])  # program tail
            # Sharded leaves leave the program as whole adapted arrays:
            # their slicing, in-group put and scatter all run host-side
            # (identical math to the eager path, so the bitwise
            # fused-vs-eager oracle holds for them too).
            sh_leaves = [leaves[i] for i in sh_idx]
            return flats, statuses, new_state, sh_leaves

        # Finish: the host drain — win_update (or the push-sum collect)
        # per bucket window with ``commit=False`` — then ONE jitted
        # rebuild+merge program whose argument path is where the fresh
        # host arrays re-enter jax: the jit call boundary converts a
        # batch of donor-less numpy operands in one pass, measured ~5x
        # cheaper than per-array ``commit_to_jax`` re-entry and ~8x
        # cheaper than embedding the drain as an ordered ``io_callback``
        # (the callback machinery's device round-trip dwarfs the fold it
        # wraps).  Ordering needs no program token — the step blocks on
        # the put statuses before the drain runs.
        def _drain_host():
            out = [
                W.win_update_then_collect(
                    name, require_mutex=require_mutex, commit=False)
                if accumulate else
                W.win_update(name, require_mutex=require_mutex,
                             commit=False)
                for name in prog.names]
            if prog.shard_name is not None:
                # Explicit partial weights: out-of-group staging stays
                # pending and never leaks into the sharded average.
                out.append(W.win_update(
                    prog.shard_name, require_mutex=require_mutex,
                    commit=False, **opt._shard_update_kwargs))
            return tuple(out)

        prog.finish_host_drain = _drain_host

        if d is not None and opt._layout == "rank":
            mask = np.zeros(opt._n, bool)
            mask[opt._owned] = True
        else:
            mask = None

        shapes, dtypes = opt._shapes, opt._dtypes
        bucket_splits = opt._bucket_splits
        treedef = jax.tree_util.tree_structure(params)

        def _rebuild_merge(params_t, sh_scattered, combined):
            leaves_out = [None] * len(shapes)
            for bi, idxs in enumerate(buckets):
                splits = bucket_splits[bi]
                parts = (jnp.split(combined[bi], list(splits[:-1]), axis=1)
                         if len(idxs) > 1 else [combined[bi]])
                for p, i in zip(parts, idxs):
                    leaves_out[i] = jnp.reshape(p, shapes[i]).astype(
                        dtypes[i])
            for i, leaf in zip(sh_idx, sh_scattered):
                leaves_out[i] = jnp.asarray(leaf).astype(dtypes[i])
            new_t = jax.tree_util.tree_unflatten(treedef, leaves_out)
            if mask is None:
                return new_t

            def one(p, q):
                m = jnp.asarray(
                    mask.reshape((-1,) + (1,) * (jnp.ndim(q) - 1)))
                return jnp.where(m, q, p)
            return jax.tree.map(one, params_t, new_t)

        # ``combined`` is consumed as inputs only (the caller keeps the
        # drain views for the consensus sampler) — returning it would
        # force XLA to materialize an output copy of every bucket flat.
        def _finish(params_t, sh_scattered, *combined):
            return _rebuild_merge(params_t, sh_scattered, combined)

        t0 = time.monotonic()
        step_fn = jax.jit(_step)
        try:  # AOT so compile time is observable separately from step time
            step_fn = step_fn.lower(params, grads, base_state).compile()
        except Exception:  # noqa: BLE001 — plain jit compiles on first call
            pass
        prog.step_fn = step_fn
        prog.finish_fn = jax.jit(_finish)
        telemetry.observe("bf_fused_step_compile_seconds",
                          time.monotonic() - t0)
        self.builds += 1
        return prog

    # -- dispatch ----------------------------------------------------------

    def step(self, params, grads, state, *, family: str,
             dst_weights=None, self_weight=None,
             require_mutex: bool = False, pre_drain=None):
        """One fused training step; raises :class:`FusedFallback` when
        this configuration cannot take the fused path (the caller then
        runs the eager step — the bitwise oracle)."""
        import jax
        from bluefog_tpu.optim.functional import DistOptState
        from bluefog_tpu.utils import telemetry

        opt = self.opt
        d = self._check_eligible(params)
        avals = tuple(
            (tuple(np.shape(x)), str(getattr(x, "dtype", np.float32)))
            for x in jax.tree_util.tree_leaves(params))
        treedef = jax.tree_util.tree_structure(params)
        key = self._key(family, treedef, avals, dst_weights, self_weight,
                        require_mutex, d)
        prog = self._programs.get(key)
        if prog is None:
            prog = self._build(family, params, grads, state.base,
                               dst_weights=dst_weights,
                               self_weight=self_weight,
                               require_mutex=require_mutex, d=d, key=key)
            # A topology/membership/config change made every older
            # program stale — a stale program must never dispatch
            # against a new generation, so evict rather than cap-rotate.
            if len(self._programs) >= _MAX_PROGRAMS:
                self._programs.clear()
            self._programs[key] = prog

        # Overlapped puts from a previous EAGER step must land before a
        # program targets the same windows.
        if hasattr(opt, "_drain_pending"):
            opt._drain_pending()

        # Host pre-dispatch: error token, sparse residual migration and
        # the associated-P refresh — the same work _ffi_put does before
        # its plan run, done once here because the run happens inside the
        # compiled program.
        tok = None
        if prog.remote_procs:
            tok = d.transport.error_token(
                {d.proc_addr[p] for p in prog.remote_procs})
        with contextlib.ExitStack() as stack:
            for name, plan in zip(prog.names, prog.plans):
                if plan is None:
                    continue
                stack.enter_context(plan.dispatch_lock)
                win = W._store.get(name)
                if plan.codec == 2:
                    with W._ef_lock:
                        taken = []
                        for _pid, grp in plan.groups:
                            for (src, dst), _w in grp:
                                r = W._ef_residuals.pop(
                                    (name, src, dst), None)
                                if r is not None:
                                    taken.append((src, dst, r))
                    for src, dst, r in taken:
                        xlaffi.push_native_residual(name, src, dst, r)
                if W._store.associated_p_enabled:
                    with win.lock:
                        for pid, grp in plan.groups:
                            xlaffi.set_group_p(
                                pid, [w * float(win.p_main[src])
                                      for (src, _dst), w in grp])
                    plan.p_set = True
                elif plan.p_set:
                    for pid, grp in plan.groups:
                        xlaffi.set_group_p(pid, [0.0] * len(grp))
                    plan.p_set = False
            if require_mutex:
                # An in-program custom call cannot hold the per-edge
                # distributed mutex around its own send; hold every
                # remote edge's mutex across the program instead — a
                # superset of the eager per-edge hold (still exclusive,
                # deterministic dst order so writers cannot deadlock).
                for (src, dst) in sorted(prog.edges):
                    if W._owns(src) and not W._owns(dst):
                        stack.enter_context(
                            W._remote_mutex(prog.names[0], dst, src))

            flats, statuses, new_base, sh_leaves = prog.step_fn(
                params, grads, state.base)
            sts = [np.asarray(s) for s in statuses]  # waits for the puts
        t_done = time.monotonic()
        # Host-sync seam: how long the host sat on the statuses AFTER the
        # program's own tail (reconcile bills it as host-sync).
        t_statuses_ns = time.monotonic_ns() if prog.probes else None

        self._check_statuses(prog, sts, flats)

        nbytes = sum(int(np.prod(f.shape)) * f.dtype.itemsize
                     for f in flats)
        W._count_win_op("accumulate" if prog.accumulate else "put",
                        nbytes, prog.edges)
        for plan in prog.plans:
            if plan is not None:
                xlaffi.record_dispatch(plan)
        if prog.n_put_calls:
            telemetry.inc("bf_fused_step_puts_total",
                          float(prog.n_put_calls))
        for bi, t_put in enumerate(prog.stamps):
            if t_put is not None:
                telemetry.observe("bf_fused_step_overlap_seconds",
                                  max(0.0, t_done - t_put), bucket=str(bi))
                prog.stamps[bi] = None

        # Host half of the put: local-edge staging writes and the
        # post-send self-publish per bucket, then ONE scoped transport
        # flush covering every bucket's sends (the eager path flushes
        # per window; one flush since the same token is the same wire
        # boundary at a fraction of the host cost).
        for name, flat in zip(prog.names, flats):
            W._fused_host_finish(
                name, flat, prog.edges, accumulate=prog.accumulate,
                self_weight=self_weight, require_mutex=require_mutex,
                remote_procs=prog.remote_procs, since=tok, flush=False)
        if prog.remote_procs:
            W._flush_transport(prog.remote_procs, since=tok)

        # Sharded half of the step, host-side: the in-group put of each
        # rank's own slice rows.  Same math and same wire primitive as
        # the eager path — only the replicated windows went through the
        # compiled program.
        sh_payload = sh_np = plan_sh = None
        if prog.shard_name is not None:
            from bluefog_tpu.ops import sharded as SHD
            plan_sh = opt._shard_plan
            sh_np = [np.asarray(x) for x in sh_leaves]
            sh_payload = np.concatenate(
                [SHD.own_shard_rows(x, sd, plan_sh.coords,
                                    plan_sh.n_shards)
                 for x, sd in zip(sh_np, opt._shard_dims)], axis=1)
            h = W.win_put_nonblocking(
                sh_payload, prog.shard_name,
                dst_weights=opt._shard_edges,
                require_mutex=require_mutex)
            W.win_wait(h)

        if pre_drain is not None:  # push-sum fence / stale-residual fold
            pre_drain()

        if prog.probes:  # host seams go into the same ring/clock
            _probes.note(_probes.DRAIN_START)
        combined = prog.finish_host_drain()
        if prog.probes:
            _probes.note(_probes.DRAIN_COMMIT)
        if prog.shard_name is not None:
            # Scatter the in-group combined rows back into each rank's
            # own slice of the adapted leaves (ghost regions untouched),
            # then let the jitted finish slot them into the tree.
            from bluefog_tpu.ops import sharded as SHD
            sh_rows = np.asarray(combined[-1])
            scattered, off = [], 0
            for x, sd, sz in zip(sh_np, opt._shard_dims,
                                 opt._shard_sizes):
                scattered.append(SHD.scatter_shard_rows(
                    x, sh_rows[:, off:off + sz], sd, plan_sh.coords,
                    plan_sh.n_shards))
                off += sz
            merged = prog.finish_fn(params, tuple(scattered),
                                    *combined[:-1])
        else:
            merged = prog.finish_fn(params, (), *combined)
        if prog.probes:
            _probes.note(_probes.FINISH_DONE)

        t = int(state.step)
        # Device arrays go in as-is (the eager step does the same): the
        # sampler gates on its cadence before touching a single element.
        pre = list(flats) + ([sh_payload] if sh_payload is not None
                             else [])
        opt._maybe_sample_consensus(t, pre, list(combined))

        # Reconcile the step's probe events into measured overlap, the
        # per-bucket issue histograms, timeline lanes and — when a
        # StepProfiler wraps this step — real phase attribution.  The
        # modeled mean is the average of modeled_overlap()'s rows,
        # (k-1)/(2k): the divergence gauge compares like with like.
        attributed = False
        if prog.probes:
            k = len(opt._buckets)
            modeled = (k - 1) / (2 * k) if k else 0.0
            summary = _probes.reconcile(k, modeled_mean=modeled,
                                        t_statuses_ns=t_statuses_ns)
            attributed = bool(summary and summary.get("attributed"))
        from bluefog_tpu.utils import profiler as _profiler
        prof = _profiler.active()
        if prof is not None:
            # Without probe attribution the profiler labels the fused
            # program's opaque remainder "fused-step", not grad-compute.
            prof.note_fused(attributed)

        telemetry.set_gauge("bf_fused_step_active", 1.0)
        self.fused_steps += 1
        return merged, DistOptState(new_base, state.step + 1)

    def _check_statuses(self, prog, sts, flats) -> None:
        """Mirror the eager dispatch's error semantics: a vanished plan
        (cache eviction race — nothing was sent) redispatches the remote
        edges host-side; any other nonzero status raises exactly like
        ``xlaffi.run_group`` would have."""
        rcs = np.concatenate(sts) if sts else np.zeros(0, np.int32)
        if not rcs.size or not (rcs != 0).any():
            return
        if (rcs[rcs != 0] == -9).all():
            self._programs.pop(prog.key, None)  # plans are stale too
            d = W._store.distrib
            op = W.OP_ACCUMULATE if prog.accumulate else W.OP_PUT
            remote_edges = tuple(
                ((s, t), w) for (s, t), w in prog.edges.items()
                if not W._owns(t))
            for name, flat in zip(prog.names, flats):
                win = W._store.get(name)
                fresh = xlaffi.prepare_put(d, win, name, op, remote_edges,
                                           per_edge=False)
                if fresh is None:
                    raise xlaffi.PlanVanished(
                        "fused step: native plan vanished and could not "
                        "be rebuilt")
                if W._store.associated_p_enabled:
                    with win.lock:
                        for pid, grp in fresh.groups:
                            xlaffi.set_group_p(
                                pid, [w * float(win.p_main[src])
                                      for (src, _dst), w in grp])
                for pid, _grp in fresh.groups:
                    xlaffi.run_group(pid, prog.tx, flat)
            return
        self._programs.pop(prog.key, None)
        bad = int(rcs[rcs != 0][0])
        raise ConnectionError(
            f"fused step: in-program window put failed (rc={bad}); "
            "the transport rejected or dropped the dispatch")
