"""Gossip-consistent membership: failure consensus for elastic gossip.

The paper's core claim is that decentralized gossip keeps training when the
gang is imperfect — but through PR 6 every layer *assumed* a fixed world:
detection existed (transport peer probes, heartbeat gauges,
``bf_straggler_score``), re-planning existed (placement search + schedule
synthesis at ``set_topology``), recovery existed (``utils/elastic.py``), and
nothing connected them.  This module is the connective tissue: a
process-granular membership view plus the consensus protocol that lets every
survivor agree on the new gang before anyone acts on it.

Design
------
* **Membership is per PROCESS** (a dead process takes all its owned ranks
  with it); the rank-level view is derived through the transport's
  ``rank_owner`` directory.
* **Messages ride the DCN window transport** as ``OP_MEMBER`` frames (JSON
  payloads) on the same per-peer FIFO TCP streams as gossip — a peer whose
  data path is wedged cannot look healthy through a side channel the data
  never takes.  No jax collective is ever used: the whole control plane
  must keep working exactly when the gang is broken, which is when a global
  collective cannot.
* **Detection** fuses the existing signals: heartbeat staleness (this
  module's own ``OP_MEMBER`` heartbeats), the transport's TCP reachability
  probe (``window._probe_missing_ranks``-style connect checks), and —
  opt-in via ``BLUEFOG_TPU_CHURN_STRAGGLER_STEPS`` — the step-lag that
  feeds ``bf_straggler_score``.
* **Consensus** is the symmetric all-survivors-agree rule: every process
  continuously broadcasts its current *proposal* (the survivor set it
  believes in) inside its heartbeats; a process commits epoch ``e -> e+1``
  exactly when every member of its proposal ``P`` has proposed the
  identical ``P`` for epoch ``e``.  The rule is deterministic in the
  proposal sets, so all survivors commit the same view without a leader,
  and the continuous rebroadcast makes it self-healing under message loss.
  Suspicion is unioned across proposers (a survivor adopts a peer's
  suspicion unless it can refute it with a fresh heartbeat), so transient
  disagreement converges instead of deadlocking.  A process that finds
  itself excluded from a committed view (its peers moved to epoch ``e+1``
  without it) marks itself EVICTED and stops participating — the graceful
  exit path for a persistently straggling or partitioned rank.

Everything here is inert unless ``BLUEFOG_TPU_CHURN=1``: no controller is
ever installed, no heartbeat is ever sent, and ``OP_MEMBER`` frames are
dropped on receipt.  The ``=0`` path is bit-identical to the pre-churn tree.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import networkx as nx

from bluefog_tpu.utils import config

__all__ = ["MembershipView", "MembershipController", "survivor_topology",
           "install", "current", "handle_wire", "health_summary"]


class MembershipView:
    """One committed membership epoch: which processes (and therefore which
    ranks) are in the gang, and what the commit removed or admitted."""

    def __init__(self, epoch: int, active_procs: Tuple[int, ...],
                 active_ranks: Tuple[int, ...],
                 removed_procs: Tuple[int, ...] = (),
                 removed_ranks: Tuple[int, ...] = (),
                 evicted: bool = False,
                 added_procs: Tuple[int, ...] = (),
                 added_ranks: Tuple[int, ...] = (),
                 added_endpoints: Optional[Dict[int, str]] = None):
        self.epoch = epoch
        self.active_procs = tuple(sorted(active_procs))
        self.active_ranks = tuple(sorted(active_ranks))
        self.removed_procs = tuple(sorted(removed_procs))
        self.removed_ranks = tuple(sorted(removed_ranks))
        # Elastic scale-UP (ops/gang.py): processes admitted BY this
        # commit, the ranks they took over, and their transport
        # endpoints ("host:port") — what the supervisor's growth
        # recovery needs to extend the rank directory before re-planning.
        self.added_procs = tuple(sorted(added_procs))
        self.added_ranks = tuple(sorted(added_ranks))
        self.added_endpoints = dict(added_endpoints or {})
        # True when THIS process is the one voted out: it must stop
        # gossiping and exit gracefully, not re-plan around itself.
        self.evicted = evicted

    def __repr__(self):
        return (f"MembershipView(epoch={self.epoch}, "
                f"active_ranks={list(self.active_ranks)}"
                + (f", added={list(self.added_ranks)}"
                   if self.added_ranks else "")
                + (", EVICTED" if self.evicted else "") + ")")


class MembershipController:
    """The consensus state machine.  Transport-agnostic by construction:
    ``send_fn(proc, payload_bytes)`` ships one membership message to a peer
    process (best effort — failures are themselves a liveness signal) and
    ``probe_fn(proc) -> bool`` answers "does this peer still accept TCP?".
    Both are injectable, so the protocol is unit-testable with an in-memory
    router and a fake clock (``now_fn``)."""

    def __init__(self, n_procs: int, my_proc: int,
                 rank_owner: Dict[int, int], *,
                 send_fn: Callable[[int, bytes], None],
                 probe_fn: Optional[Callable[[int], bool]] = None,
                 now_fn: Callable[[], float] = time.monotonic,
                 suspect_sec: Optional[float] = None,
                 straggler_steps: Optional[int] = None,
                 active=None, epoch: int = 0, joining: bool = False,
                 my_join_ranks=(), my_endpoint: Optional[str] = None):
        cfg = config.get()
        self.n_procs = n_procs
        self.my_proc = my_proc
        self.rank_owner = dict(rank_owner)
        self.send_fn = send_fn
        self.probe_fn = probe_fn
        self.now_fn = now_fn
        self.suspect_sec = (cfg.churn_suspect_ms / 1e3
                            if suspect_sec is None else suspect_sec)
        self.straggler_steps = (cfg.churn_straggler_steps
                                if straggler_steps is None
                                else straggler_steps)
        # Barrier-free async mode (BLUEFOG_TPU_ASYNC): ranks LEGITIMATELY
        # run ahead of each other between exact-collect backstops, so a
        # raw step-lag threshold would evict peers that are merely slow.
        # The lag a healthy straggler can accumulate is bounded by the
        # backstop cadence (fast ranks block at the collect fence until
        # it arrives), so the effective threshold widens by exactly
        # ASYNC_COLLECT_EVERY; with no backstop (collect_every=0) lag is
        # unbounded by design and step-lag eviction disables itself —
        # the staleness policy, not membership, absorbs slow peers.
        self._async_mode = cfg.async_mode
        self._async_collect_every = cfg.async_collect_every
        self._lock = threading.RLock()
        self.epoch = int(epoch)
        self._warned_lag_eviction_off = False
        # `active` defaults to every process (the classic fixed-gang
        # construction); a JOINING process seeds it from its join grant —
        # the committed survivor set it is asking to be admitted into.
        self.active: frozenset = (frozenset(active) if active is not None
                                  else frozenset(range(n_procs)))
        # Elastic scale-up state (ops/gang.py).  `joining`: this process
        # is a granted-but-uncommitted joiner — it proposes
        # `active | {me}` and heartbeats with its rank/endpoint claim
        # until a commit admits it.  `pending_joins`: granted joiners
        # heard from (proc -> (ranks, endpoint, first-heard monotonic));
        # they enter every proposal while their heartbeats stay fresh.
        # `joined_info`: permanent record of admitted joiners' rank/
        # endpoint claims; `joined_at_epoch`: procs admitted by the
        # CURRENT epoch's commit, gossiped so a behind peer can adopt a
        # grown view it never saw the joiner's own heartbeats for.
        self.joining = bool(joining)
        self.my_join_ranks = tuple(int(r) for r in my_join_ranks)
        self.my_endpoint = my_endpoint
        self.pending_joins: Dict[int, tuple] = {}
        self.joined_info: Dict[int, tuple] = {}
        self.joined_at_epoch: frozenset = frozenset()
        self.evicted = False
        self.changes_total = 0
        self.last_change_unix: Optional[float] = None
        # Liveness bookkeeping.  last_seen starts at construction time so a
        # peer that NEVER heartbeats (died during init) still ages out.
        now = now_fn()
        self.last_seen: Dict[int, float] = {p: now for p in self.active
                                            if p != my_proc}
        self.peer_step: Dict[int, int] = {}
        self.my_step = 0
        # proc -> (epoch, frozenset proposal, monotonic time heard).  The
        # equality check reads the latest; staleness beyond the suspect
        # window retires an entry (a withdrawn proposal must not linger).
        self.proposals: Dict[int, Tuple[int, frozenset, float]] = {}
        self._pending: List[MembershipView] = []
        # One-shot eviction verdicts: procs removed by the last commit that
        # may still be ALIVE (straggler/partition eviction).  The next tick
        # sends them the committed view once, so an evicted-but-reachable
        # rank learns it was voted out instead of — having lost everyone
        # else's heartbeats — eventually committing a lonely gang of one.
        self._notify_removed: List[int] = []

    # -- derived views -----------------------------------------------------

    def active_ranks(self, procs=None) -> Tuple[int, ...]:
        procs = self.active if procs is None else procs
        return tuple(sorted(r for r, p in self.rank_owner.items()
                            if p in procs))

    def view(self) -> MembershipView:
        with self._lock:
            return MembershipView(self.epoch, tuple(self.active),
                                  self.active_ranks(),
                                  evicted=self.evicted)

    # -- wire --------------------------------------------------------------

    def _payload(self, prop: Optional[frozenset]) -> bytes:
        body = {
            "k": "hb",
            "proc": self.my_proc,
            "epoch": self.epoch,
            "step": self.my_step,
            "active": sorted(self.active),
            "prop": None if prop is None else sorted(prop),
        }
        # Join keys ride the heartbeat ONLY when a join is actually in
        # flight or was just committed — with no joins anywhere the
        # payload stays byte-identical to the pre-join wire (tested).
        if self.joining:
            body["join"] = list(self.my_join_ranks)
            if self.my_endpoint:
                body["ep"] = self.my_endpoint
        if self.joined_at_epoch:
            # Enough for a peer that never heard the joiner directly to
            # adopt the grown view: who joined, which ranks they own, and
            # where their transport listens.
            body["joined"] = sorted(self.joined_at_epoch)
            body["joined_ranks"] = {
                str(p): list(self.joined_info[p][0])
                for p in sorted(self.joined_at_epoch)
                if p in self.joined_info}
            body["joined_eps"] = {
                str(p): self.joined_info[p][1]
                for p in sorted(self.joined_at_epoch)
                if p in self.joined_info and self.joined_info[p][1]}
        return json.dumps(body).encode()

    def _adopt_joined_info(self, msg: dict) -> None:
        """Fold a heartbeat's joined-proc claims (ranks + endpoints) into
        ``joined_info`` so an adopted grown view can extend ``rank_owner``
        even when this process never saw the joiner's own heartbeats
        (caller holds the lock)."""
        ranks = msg.get("joined_ranks") or {}
        eps = msg.get("joined_eps") or {}
        for p_s, rr in ranks.items():
            p = int(p_s)
            if p not in self.joined_info:
                self.joined_info[p] = (tuple(int(r) for r in rr),
                                       eps.get(p_s))

    def on_message(self, msg: dict) -> None:
        """Apply one inbound membership message (drain-thread entry: takes
        only the controller lock, never blocks on peers)."""
        with self._lock:
            if self.evicted:
                return
            p = int(msg.get("proc", -1))
            if p < 0 or p == self.my_proc:
                return
            now = self.now_fn()
            self.last_seen[p] = now
            if "step" in msg:
                self.peer_step[p] = int(msg["step"])
            self._adopt_joined_info(msg)
            if "join" in msg and p not in self.active:
                self._note_pending_join(
                    p, msg.get("join") or [], msg.get("ep"), now)
            their_epoch = int(msg.get("epoch", 0))
            their_active = frozenset(int(x) for x in msg.get("active", []))
            if their_epoch > self.epoch and their_active:
                # A peer committed ahead of us (our agreement message was
                # still in flight when it crossed the threshold).  The
                # commit rule is deterministic, so adopting its view is the
                # same commit we were about to make — unless the view
                # excludes us, which is the eviction verdict.  A JOINING
                # process is different: it was never a member, so a newer
                # view without it (the gang shrank again while its
                # admission was in flight) is not a verdict — it adopts
                # the view as its new base and keeps proposing itself.
                if self.my_proc in their_active:
                    self._commit(their_epoch, their_active)
                elif self.joining:
                    self._rebase_while_joining(their_epoch, their_active)
                else:
                    self._evict()
                return
            if (their_epoch == self.epoch and self.epoch > 0
                    and their_active and their_active != self.active):
                # Same-epoch divergent views: two processes raced their
                # commits from proposal snapshots taken at different
                # instants.  Reconcile INCUMBENTS by INTERSECTION —
                # monotone (a proc both sides already carried survives
                # only in both), deterministic, both sides converge under
                # continuous heartbeats — and JOINERS by UNION: a proc
                # admitted at this epoch appears in a view precisely
                # because its committer verified full agreement including
                # the joiner, and the join announcement may simply not
                # have reached the other committer before its snapshot.
                # (The superset extension of the PR-7 intersection rule:
                # with no joins the union term is empty and the rule is
                # exactly the old one.)
                their_joined = frozenset(
                    int(x) for x in msg.get("joined") or [])
                joiners = ((self.joined_at_epoch | their_joined)
                           & (self.active | their_active))
                merged = (self.active & their_active) | joiners
                if self.my_proc not in merged:
                    if self.joining:
                        self._rebase_while_joining(self.epoch, merged)
                    else:
                        self._evict()
                elif merged and merged != self.active:
                    self._commit(self.epoch, merged)
                return
            prop = msg.get("prop")
            if their_epoch == self.epoch:
                if prop is not None:
                    self.proposals[p] = (their_epoch,
                                         frozenset(int(x) for x in prop),
                                         now)
                else:
                    # An explicit withdrawal: the peer no longer suspects
                    # anyone.  Clearing the entry matters — a commit
                    # evaluated against a lingering withdrawn proposal
                    # could evict a live rank on votes already retracted.
                    self.proposals.pop(p, None)

    # -- elastic scale-up (ops/gang.py) ------------------------------------

    def _note_pending_join(self, proc: int, ranks, endpoint,
                           now: float) -> None:
        """Register a granted joiner's admission claim (lock held).  The
        claim is validated against the live world: its ranks must be
        VACANT (owned by no active proc) and must not collide with an
        earlier pending claim — a colliding later claim is ignored (the
        grantor-side reservation makes collisions a cross-grantor race,
        and dropping the newcomer deterministically keeps every
        controller's proposal convergent)."""
        ranks = tuple(int(r) for r in ranks)
        if proc in self.pending_joins:
            # Refresh liveness only; the claim itself is immutable.
            old = self.pending_joins[proc]
            self.pending_joins[proc] = (old[0], endpoint or old[1], old[2])
            return
        active_ranks = set(self.active_ranks())
        claimed = {r for info in self.pending_joins.values()
                   for r in info[0]}
        if (set(ranks) & active_ranks) or (set(ranks) & claimed) \
                or not ranks:
            from bluefog_tpu.utils.logging import get_logger
            get_logger().warning(
                "membership: join claim from proc %d for ranks %s "
                "collides with live or already-claimed ranks — ignored",
                proc, list(ranks))
            return
        self.pending_joins[proc] = (ranks, endpoint, now)

    def _rebase_while_joining(self, epoch: int, active: frozenset) -> None:
        """The gang committed past us while our admission was in flight
        (lock held): adopt the newer survivor set as the join's new base
        — no view is emitted (we were never a member, there is nothing to
        recover) and the next tick proposes ``active | {me}`` again."""
        self.epoch = int(epoch)
        self.active = frozenset(active)
        self.proposals.clear()
        now = self.now_fn()
        for p in self.active:
            if p != self.my_proc:
                self.last_seen.setdefault(p, now)
        from bluefog_tpu.utils.logging import get_logger
        get_logger().info(
            "membership: gang committed epoch %d while this process was "
            "still joining — rebasing the join on the new survivor set "
            "%s", self.epoch, sorted(self.active))

    def note_join(self, proc: int, ranks, endpoint: Optional[str]) -> None:
        """Grantor-side entry: record the joiner this process just granted
        so it enters our proposals immediately (its own heartbeats will
        reach the rest of the gang)."""
        with self._lock:
            if self.evicted or proc in self.active:
                return
            self._note_pending_join(proc, ranks, endpoint, self.now_fn())

    def peer_endpoint_hint(self, proc: int) -> Optional[tuple]:
        """(host, port) of a proc known only through the join protocol —
        what the supervisor's send path falls back to for peers not yet in
        the transport directory (pending or freshly admitted joiners)."""
        with self._lock:
            info = self.pending_joins.get(proc) \
                or self.joined_info.get(proc)
        ep = info[1] if info else None
        if not ep:
            return None
        try:
            from bluefog_tpu.ops.gang import _ep_addr
            return _ep_addr(ep)
        except ValueError:
            return None

    # -- detection + consensus tick ---------------------------------------

    def note_step(self, step: int) -> None:
        with self._lock:
            self.my_step = int(step)

    def _straggler_bound(self) -> int:
        """Effective step-lag eviction threshold: 0 = lag eviction off.
        Lockstep mode: the raw CHURN_STRAGGLER_STEPS knob.  Async mode:
        widened by the collect-backstop cadence (the lag a merely-slow
        peer legitimately reaches); disabled entirely with no backstop —
        any threshold would evict healthy slow peers the staleness
        policy is already absorbing."""
        if not self.straggler_steps:
            return 0
        if not self._async_mode:
            return self.straggler_steps
        if not self._async_collect_every:
            if not self._warned_lag_eviction_off:
                self._warned_lag_eviction_off = True
                from bluefog_tpu.utils.logging import get_logger
                get_logger().warning(
                    "churn: BLUEFOG_TPU_CHURN_STRAGGLER_STEPS is set but "
                    "BLUEFOG_TPU_ASYNC=1 with no collect backstop "
                    "(BLUEFOG_TPU_ASYNC_COLLECT_EVERY=0) makes step lag "
                    "unbounded by design — step-lag eviction is disabled")
            return 0
        return self.straggler_steps + self._async_collect_every

    def _stale_peers(self, now: float) -> List[int]:
        """Active peers whose heartbeats have gone stale (lock held by the
        caller) — the probe candidates."""
        fresh_cut = now - self.suspect_sec
        return [p for p in sorted(self.active)
                if p != self.my_proc
                and self.last_seen.get(p, 0.0) < fresh_cut]

    def _suspects(self, now: float, probes: Optional[dict] = None
                  ) -> frozenset:
        """Fuse the liveness signals into the set of suspected processes.

        ``probes`` carries pre-collected reachability verdicts for the
        stale peers ({proc: bool}); the blocking TCP probes themselves run
        OUTSIDE the controller lock (see :meth:`tick`) — a probe hanging
        to its timeout on a lost host must never starve the drain thread's
        ``on_message`` into making healthy peers look stale too.  A stale
        peer with no verdict (``summary()`` passes an empty dict: the
        /healthz path must not do network I/O) is suspected only on the
        hard-silence window."""
        out = set()
        fresh_cut = now - self.suspect_sec
        straggler_bound = self._straggler_bound()
        for p in sorted(self.active):
            if p == self.my_proc:
                continue
            stale = self.last_seen.get(p, 0.0) < fresh_cut
            if stale:
                verdict = None if probes is None else probes.get(p)
                if verdict is False or (self.probe_fn is None
                                        and probes is None):
                    out.add(p)  # silent AND unreachable: dead
                elif self.last_seen.get(p, 0.0) < now - 3 * self.suspect_sec:
                    # Reachable (or unprobed) but silent for 3x the
                    # window: its listener answers TCP but nothing flows
                    # (wedged process, or a chaos partition dropping its
                    # outbound traffic).
                    out.add(p)
            elif (straggler_bound
                  and self.my_step - self.peer_step.get(p, self.my_step)
                  > straggler_bound):
                # Alive but persistently behind: the straggler-eviction
                # policy (opt-in) proposes it out so the survivors stop
                # waiting on its gossip.
                out.add(p)
        # Union of suspicion: adopt a proposer's suspicion of q unless we
        # can refute it with a fresh heartbeat from q — transiently
        # disagreeing survivors converge to the same proposal instead of
        # deadlocking on each other's partial views.
        for p, (ep, prop, heard) in list(self.proposals.items()):
            if ep != self.epoch or heard < fresh_cut:
                self.proposals.pop(p, None)
                continue
            for q in self.active - prop:
                if q != self.my_proc and self.last_seen.get(q, 0.0) < fresh_cut:
                    out.add(q)
        return frozenset(out)

    def tick(self) -> None:
        """One detection + consensus round: re-evaluate suspicion, heartbeat
        every active peer (carrying the current proposal), and commit when
        all survivors agree.  Called on the supervisor's heartbeat cadence.

        The blocking TCP probes run between two short lock holds: a probe
        that hangs to its timeout (lost host) delays only this heartbeat
        round, never the drain thread's inbound message handling."""
        with self._lock:
            if self.evicted:
                return
            now = self.now_fn()
            candidates = self._stale_peers(now)
        probes: Dict[int, bool] = {}
        for p in candidates:
            if self.probe_fn is None:
                probes[p] = False  # no probe available: silence decides
            else:
                try:
                    probes[p] = bool(self.probe_fn(p))
                except Exception:  # noqa: BLE001 — a probe crash = down
                    probes[p] = False
        with self._lock:
            if self.evicted:
                return
            now = self.now_fn()
            suspects = self._suspects(now, probes)
            # A granted joiner that died (or went silent) before its
            # commit simply ages out of the pending set — its claim must
            # not keep every survivor proposing a grown view forever.
            fresh_cut = now - self.suspect_sec
            for p in [p for p, info in self.pending_joins.items()
                      if max(info[2], self.last_seen.get(p, 0.0))
                      < fresh_cut]:
                self.pending_joins.pop(p, None)
            joins = frozenset(self.pending_joins)
            prop = None
            if suspects or joins or self.joining:
                prop = frozenset((self.active - suspects) | joins
                                 | ({self.my_proc} if self.joining
                                    else frozenset()))
            if prop is not None:
                self.proposals[self.my_proc] = (self.epoch, prop, now)
            else:
                self.proposals.pop(self.my_proc, None)
            payload = self._payload(prop)
            targets = [p for p in sorted(self.active | joins)
                       if p != self.my_proc and p not in suspects]
            if prop is not None:
                self._maybe_commit(prop)
            if self._notify_removed:
                # Deliver eviction verdicts with the COMMITTED state (the
                # payload above may predate a commit _maybe_commit just
                # made), best effort, once.
                payload = self._payload(None)
                targets = targets + self._notify_removed
                self._notify_removed = []
        # Sends happen OUTSIDE the lock: send_fn may block briefly on a
        # backpressured queue, and the drain thread must keep delivering
        # inbound membership traffic meanwhile.
        for p in targets:
            try:
                self.send_fn(p, payload)
            except Exception:  # noqa: BLE001 — a failed send IS the signal
                pass

    def _maybe_commit(self, prop: frozenset) -> None:
        """Commit iff every member of the proposal has proposed exactly it
        for the current epoch (caller holds the lock)."""
        if self.my_proc not in prop:
            self._evict()
            return
        for q in prop:
            if q == self.my_proc:
                continue
            entry = self.proposals.get(q)
            if entry is None or entry[0] != self.epoch or entry[1] != prop:
                return
        self._commit(self.epoch + 1, prop)

    def _commit(self, epoch: int, active: frozenset) -> None:
        removed = frozenset(self.active) - active
        added = frozenset(active) - self.active
        now = self.now_fn()
        added_eps: Dict[int, str] = {}
        admission_secs = []
        for p in sorted(added):
            # The admitted proc's rank/endpoint claim: from its own join
            # heartbeats (pending_joins), from a peer's gossip about an
            # earlier commit (joined_info), or — when WE are the joiner —
            # from the grant itself.
            info = self.pending_joins.pop(p, None)
            if info is not None:
                ranks, ep, heard = info
                admission_secs.append(max(0.0, now - heard))
            elif p == self.my_proc:
                ranks, ep = self.my_join_ranks, self.my_endpoint
            elif p in self.joined_info:
                ranks, ep = self.joined_info[p]
            else:
                from bluefog_tpu.utils.logging import get_logger
                get_logger().warning(
                    "membership: adopted a view admitting proc %d with no "
                    "rank claim on record — its ranks stay unowned until "
                    "its gossip arrives", p)
                continue
            for r in ranks:
                self.rank_owner[r] = p
            self.joined_info[p] = (tuple(ranks), ep)
            if ep:
                added_eps[p] = ep
            self.last_seen[p] = now
        self.joined_at_epoch = added
        if self.my_proc in added:
            self.joining = False
        view = MembershipView(
            epoch, tuple(active), self.active_ranks(active),
            removed_procs=tuple(removed),
            # After the reassignment above, so a rank revived by this
            # very commit is never reported as removed.
            removed_ranks=self.active_ranks(removed),
            added_procs=tuple(added),
            added_ranks=tuple(sorted(
                r for p in added for r in self.joined_info.get(p, ((),))[0]
            )),
            added_endpoints=added_eps)
        self.epoch = epoch
        self.active = frozenset(active)
        self.proposals.clear()
        self.changes_total += 1
        self.last_change_unix = time.time()
        self._pending.append(view)
        self._notify_removed = sorted(removed)
        self._publish_telemetry(n_joins=len(added),
                                admission_secs=admission_secs)
        from bluefog_tpu.utils.logging import get_logger
        get_logger().warning(
            "membership: epoch %d committed — active ranks %s (removed "
            "ranks %s%s)", epoch, list(view.active_ranks),
            list(view.removed_ranks),
            f", added ranks {list(view.added_ranks)}"
            if view.added_ranks else "")

    def _evict(self) -> None:
        self.evicted = True
        self.changes_total += 1
        self.last_change_unix = time.time()
        self._pending.append(MembershipView(
            self.epoch + 1, (), (), removed_procs=(self.my_proc,),
            removed_ranks=self.active_ranks({self.my_proc}),
            evicted=True))
        from bluefog_tpu.utils.logging import get_logger
        get_logger().warning(
            "membership: this process (proc %d) was voted out of the gang "
            "— stopping gossip participation", self.my_proc)

    def poll_change(self) -> Optional[MembershipView]:
        """One committed-but-unapplied membership change, oldest first
        (None when the view is stable).  The supervisor drains this at step
        boundaries and performs the actual re-plan."""
        with self._lock:
            return self._pending.pop(0) if self._pending else None

    # -- telemetry ---------------------------------------------------------

    def _publish_telemetry(self, n_joins: int = 0,
                           admission_secs=()) -> None:
        if current() is not self:
            # Only the process's INSTALLED controller owns the process-wide
            # gauges (hermetic tests wire several controllers in one
            # process; their commits must not multiply the counters).
            return
        from bluefog_tpu.utils import telemetry
        telemetry.inc("bf_membership_changes_total")
        telemetry.set_gauge("bf_active_ranks", len(self.active_ranks()))
        telemetry.set_gauge("bf_membership_epoch", self.epoch)
        if n_joins:
            telemetry.inc("bf_membership_joins_total", float(n_joins))
        for sec in admission_secs:
            # First-heard join claim -> committed grow epoch, as observed
            # by this survivor: the admission latency an operator tunes
            # heartbeat/suspect windows against.
            telemetry.observe("bf_join_admission_seconds", float(sec))
        if self.last_change_unix is not None:
            telemetry.set_gauge("bf_churn_last_change_timestamp",
                                self.last_change_unix)

    def summary(self) -> dict:
        """The /healthz "membership" block (and the %bfstat line).  No
        network I/O: suspicion is reported from heartbeat staleness alone
        (empty probe verdicts), so a monitoring scrape can never stall on
        a dead host's connect timeout."""
        with self._lock:
            now = self.now_fn()
            suspects = sorted(self._suspects(now, {})) \
                if not self.evicted else []
            out = {
                "epoch": self.epoch,
                "active_ranks": list(self.active_ranks()),
                "world_ranks": len(self.rank_owner),
                "changes_total": self.changes_total,
                "suspect_ranks": sorted(
                    r for p in suspects for r, o in self.rank_owner.items()
                    if o == p),
                "evicted": self.evicted,
                "last_change_unix": self.last_change_unix,
            }
            if self.pending_joins:
                # Admission in flight: the ranks granted joiners are
                # claiming — what /healthz shows between the grant and
                # the committed grow epoch.
                out["pending_join_ranks"] = sorted(
                    r for info in self.pending_joins.values()
                    for r in info[0])
            if self.joining:
                out["joining"] = True
            return out


# ---------------------------------------------------------------------------
# Survivor re-planning
# ---------------------------------------------------------------------------

def survivor_topology(n: int, active_ranks, builder=None) -> nx.DiGraph:
    """A virtual topology over the full ``n``-rank world that gossips only
    among ``active_ranks``: the builder's graph over the survivors
    (relabeled onto their global rank ids) with every dead rank isolated
    under a self-loop of weight 1.

    The effective weight matrix stays doubly stochastic: the survivor
    submatrix is the builder's doubly-stochastic matrix (every standard
    generator in ``topology.py`` funnels through ``_circulant``), and the
    dead rows/columns are exactly the identity.  Keeping the dead ranks as
    isolated nodes means ``set_topology`` needs no world-size surgery —
    the mesh, the schedule compiler and the placement/synthesis pipeline
    all see an ordinary ``n``-node topology with no edges to price on the
    dead links."""
    from bluefog_tpu import topology as topology_util
    active = sorted(int(r) for r in active_ranks)
    if not active:
        raise ValueError("survivor_topology: no active ranks")
    if len(set(active)) != len(active) or active[0] < 0 or active[-1] >= n:
        raise ValueError(
            f"survivor_topology: active ranks {active} must be distinct "
            f"ranks in range({n})")
    if builder is None:
        builder = topology_util.ExponentialGraph
    g = builder(len(active))
    topo = nx.relabel_nodes(g, dict(enumerate(active)), copy=True)
    topo.add_nodes_from(range(n))
    for r in range(n):
        if r not in topo or topo.degree(r) == 0:
            topo.add_edge(r, r, weight=1.0)
    return topo


# ---------------------------------------------------------------------------
# Process-wide registry (the transport's drain thread and /healthz both
# need to find the live controller without import cycles)
# ---------------------------------------------------------------------------

_active_controller: Optional[MembershipController] = None
_registry_lock = threading.Lock()


def install(ctrl: Optional[MembershipController]) -> None:
    global _active_controller
    with _registry_lock:
        _active_controller = ctrl


def current() -> Optional[MembershipController]:
    return _active_controller


def handle_wire(payload) -> None:
    """Entry point for inbound ``OP_MEMBER`` frames (called from the window
    store's drain-thread apply).  Payload is a zero-copy view into the recv
    buffer — decoded here, never retained.  Dropped silently when no
    controller is installed (churn off, or a straggling peer still
    heartbeating after our shutdown)."""
    ctrl = _active_controller
    if ctrl is None:
        return
    try:
        msg = json.loads(bytes(payload).decode())
    except (ValueError, UnicodeDecodeError):
        from bluefog_tpu.utils.logging import get_logger
        get_logger().warning("membership: undecodable OP_MEMBER frame "
                             "dropped (%d bytes)", len(payload))
        return
    ctrl.on_message(msg)


def health_summary() -> Optional[dict]:
    """The membership block for ``/healthz`` (None when churn is off)."""
    ctrl = _active_controller
    if ctrl is None:
        return None
    return ctrl.summary()
