"""Chunked softmax cross-entropy: O(chunk x vocab) memory lm-head loss.

Long-context training on a single chip is bounded by the lm-head logits, not
attention (flash attention is O(S); the ``(S, vocab)`` f32 logits are not —
8.4 GB at S=64k, vocab=32k).  This computes the standard next-token loss
without ever materializing the full logits: a ``lax.scan`` over sequence
chunks projects each chunk, reduces it to its per-row ``logsumexp`` and the
correct-token logit, and drops the chunk logits immediately.
``jax.checkpoint`` on the chunk body extends the same economy to the
backward (each chunk's logits are recomputed, never stored).

The result is bit-comparable to
``optax.softmax_cross_entropy_with_integer_labels(h @ W, targets)`` up to
f32 reduction order.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["chunked_softmax_cross_entropy"]


def chunked_softmax_cross_entropy(hidden, lm_head, targets, *,
                                  chunk: int = 1024):
    """Mean next-token cross-entropy over ``(B, S)`` without full logits.

    ``hidden``: (B, S, E) final-layer activations; ``lm_head``: (E, V)
    projection (pass ``params["lm_head"]["kernel"]``); ``targets``: (B, S)
    int labels.  ``chunk`` rows of logits exist at a time (per batch row).
    """
    B, S, E = hidden.shape
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    # Largest divisor of S <= chunk, so awkward S (odd, prime factors) still
    # gets the biggest legal chunk instead of degrading to 1 via halving.
    c = min(chunk, S)
    while S % c:
        c -= 1
    n_chunks = S // c

    h = hidden.reshape(B, n_chunks, c, E).transpose(1, 0, 2, 3)  # (n,B,c,E)
    t = targets.reshape(B, n_chunks, c).transpose(1, 0, 2)       # (n,B,c)

    @jax.checkpoint
    def chunk_loss(h_c, t_c):
        logits = jnp.einsum("bce,ev->bcv", h_c.astype(jnp.float32),
                            lm_head.astype(jnp.float32))
        lse = jax.nn.logsumexp(logits, axis=-1)                  # (B, c)
        correct = jnp.take_along_axis(logits, t_c[..., None],
                                      axis=-1)[..., 0]
        return jnp.sum(lse - correct)

    def body(acc, xs):
        h_c, t_c = xs
        return acc + chunk_loss(h_c, t_c), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (h, t))
    return total / (B * S)
