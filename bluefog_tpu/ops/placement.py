"""Physical-topology model, routing cost model and rank-placement optimizer.

The schedule compiler (``ops/schedule.py`` + ``ops/schedule_opt.py``) is
exact about *logical* cost — which edges exist and how few ppermute rounds
carry them — but blind to the *physical* network: ``bf.init()`` lays ranks
onto devices in raw enumeration order, so one Exp2 edge between logical
neighbors may cross the whole ICI torus (or a DCN slice boundary) while
another round's edges pile onto the same link.  TACCL (arxiv 2111.04867)
and HiCCL (arxiv 2408.05962) show that mapping the communication pattern
onto the interconnect — placement plus contention-aware packing — is where
the next multiple of bandwidth lives.  This module supplies the three
pieces:

  * **Interconnect model** (:class:`TorusModel`): the TPU 2/3-D torus built
    from ``device.coords`` + ``slice_index`` (inter-slice traffic crosses a
    shared per-slice-pair DCN link, weighted ``dcn_link_cost`` ICI hops),
    the synthetic ``BLUEFOG_TPU_FAKE_TORUS=RxC[xZ]`` torus for container
    testing, and the flat-CPU fallback (no coords, no fake torus → no
    model, placement is a no-op — today's behavior).
  * **Cost model**: every schedule edge is routed dimension-ordered
    (shortest wrap direction per dimension, ties broken toward +);
    per-round link loads come from counting crossings, and a compiled
    schedule reports ``max_link_load`` (max over rounds of the busiest
    link's weighted load — the contention peak), ``hop_bytes`` (total
    weighted crossings at unit payload) and ``serial_link_time`` (sum of
    per-round bottlenecks — the modeled execution time of the round
    sequence).
  * **Placement optimizer** (:func:`optimize_placement`): search over the
    logical-rank → physical-device permutation minimizing
    ``(max_link_load, hop_bytes)`` lexicographically, jointly over every
    phase of the supplied schedules (one mesh serves all phases).  Greedy
    affinity seed + simulated-annealing refinement with a seeded PRNG —
    fully deterministic, so every SPMD process computes the identical
    permutation.  The identity permutation is always evaluated and wins
    ties, so shift-structured placements (ring/Exp2 on a matching torus)
    are never made worse.

The permutation is applied by ``basics.set_topology`` as a *device-order*
permutation of the mesh: mesh position ``i`` still computes logical rank
``i``'s row with the unchanged weight matrix — only the physical chip
underneath moves — so results are bit-identical with placement on or off
(``BLUEFOG_TPU_PLACEMENT=0`` restores enumeration order exactly).
"""

from __future__ import annotations

import hashlib
import math
import threading
import weakref
from dataclasses import dataclass
from functools import cached_property
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "TorusModel",
    "MeasuredModel",
    "CostReport",
    "PlacementResult",
    "parse_torus_spec",
    "synthetic_torus",
    "build_model",
    "schedule_rounds",
    "schedule_cost",
    "optimize_placement",
    "set_active",
    "active",
    "predicted_edge_cost",
    "modeled_schedule_hops",
]


# ---------------------------------------------------------------------------
# Interconnect model
# ---------------------------------------------------------------------------

@dataclass(frozen=True, eq=False)
class TorusModel:
    """A wrap-around torus of chips plus optional inter-slice DCN links.

    ``dims``         — per-dimension torus extents (2-D or 3-D).
    ``device_node``  — device index → global node id; several devices may
                       share a node (TPU v2/v3 megacore pairs: 0 hops).
                       Node id = ``slice * prod(dims) + ravel(coords)``.
    ``n_slices``     — number of DCN-connected slices.
    ``dcn_link_cost``— load/hop weight of one DCN crossing relative to one
                       ICI hop (DCN links are the scarce resource; a
                       crossing both costs more hop-bytes and saturates
                       its shared link faster).
    ``wrap``         — per-dimension wraparound flags; empty = every
                       dimension wraps (a full torus).  Sub-pod TPU slices
                       are *meshes* on most axes — modeling wrap links
                       that do not physically exist would let the
                       optimizer route traffic over them and install a
                       placement that is actively wrong on hardware, so
                       :func:`build_model` decides per dimension (see the
                       ``BLUEFOG_TPU_TORUS_WRAP`` policy there).

    Link id space: intra-torus links first (``node * 2*ndims + dim*2 +
    direction``), then one directed DCN link per ordered slice pair.
    """
    name: str
    dims: Tuple[int, ...]
    device_node: Tuple[int, ...]
    n_slices: int = 1
    dcn_link_cost: float = 4.0
    wrap: Tuple[bool, ...] = ()

    @property
    def wrap_dims(self) -> Tuple[bool, ...]:
        return self.wrap if self.wrap else (True,) * len(self.dims)

    # These scalars sit on the routing hot path (millions of calls while
    # building the route table) — plain-int math, cached on the instance
    # (cached_property writes the frozen dataclass's __dict__ directly).
    @cached_property
    def nodes_per_slice(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def n_nodes(self) -> int:
        return self.nodes_per_slice * self.n_slices

    @property
    def first_dcn_link(self) -> int:
        """First DCN link id — the ICI/DCN boundary of the link id space
        (ids below are intra-torus, ids from here on are the per-slice-
        pair DCN links).  The single source of truth consumers splitting
        per-level costs must use, never a re-derived layout formula."""
        return self.n_nodes * 2 * len(self.dims)

    @property
    def n_links(self) -> int:
        return self.first_dcn_link + self.n_slices * self.n_slices

    @cached_property
    def link_weights(self) -> np.ndarray:
        """(n_links,) per-crossing weight: 1.0 ICI, ``dcn_link_cost`` DCN."""
        w = np.ones(self.n_links)
        w[self.first_dcn_link:] = self.dcn_link_cost
        return w

    # -- routing ------------------------------------------------------------

    def _coords(self, node: int) -> Tuple[int, List[int]]:
        sl, flat = divmod(node, self.nodes_per_slice)
        coords = []
        for extent in reversed(self.dims):
            coords.append(flat % extent)
            flat //= extent
        return sl, coords[::-1]

    def _intra_link(self, sl: int, coords: List[int], dim: int,
                    forward: bool) -> int:
        flat = 0
        for c, extent in zip(coords, self.dims):
            flat = flat * extent + c
        node = sl * self.nodes_per_slice + flat
        return node * 2 * len(self.dims) + dim * 2 + (0 if forward else 1)

    def route(self, a: int, b: int) -> np.ndarray:
        """Directed link ids crossed by a packet from node ``a`` to ``b``.

        Dimension-ordered: resolve dim 0 fully, then dim 1, ... taking the
        shorter wrap direction per dimension when the dimension wraps
        (ties go forward, so every rank routes deterministically), the
        direct mesh path otherwise.  Inter-slice packets cross exactly
        the shared ``slice_a → slice_b`` DCN link — intra-slice approach
        hops are deliberately not modeled (the DCN link, not the on-slice
        feed, is the bottleneck resource).
        """
        cache: Dict[Tuple[int, int], np.ndarray] = self.__dict__.setdefault(
            "_route_cache", {})
        hit = cache.get((a, b))
        if hit is not None:
            return hit
        sa, ca = self._coords(a)
        sb, cb = self._coords(b)
        if sa != sb:
            ids = np.asarray([self.first_dcn_link
                              + sa * self.n_slices + sb], np.int64)
            cache[(a, b)] = ids
            return ids
        links: List[int] = []
        cur = list(ca)
        for dim, (extent, wraps) in enumerate(zip(self.dims,
                                                  self.wrap_dims)):
            if wraps:
                fwd = (cb[dim] - cur[dim]) % extent
                if fwd == 0:
                    continue
                steps, forward = (fwd, True) if fwd <= extent - fwd \
                    else (extent - fwd, False)
            else:
                diff = cb[dim] - cur[dim]
                if diff == 0:
                    continue
                steps, forward = abs(diff), diff > 0
            for _ in range(steps):
                links.append(self._intra_link(sa, cur, dim, forward))
                cur[dim] = (cur[dim] + (1 if forward else -1)) % extent
        ids = np.asarray(links, np.int64)
        cache[(a, b)] = ids
        return ids

    def distance(self, a: int, b: int) -> float:
        """Weighted routing distance between two nodes (greedy-seed metric)."""
        if a == b:
            return 0.0
        sa, ca = self._coords(a)
        sb, cb = self._coords(b)
        if sa != sb:
            return self.dcn_link_cost
        return float(sum(
            min((y - x) % e, (x - y) % e) if w else abs(y - x)
            for x, y, e, w in zip(ca, cb, self.dims, self.wrap_dims)))

    # Above this node count the dense (n_nodes² × max-route-length) table
    # the vectorized evaluator gathers from stops being worth its build
    # time/memory; the per-pair route cache path covers the tail.
    _VECTOR_TABLE_MAX_NODES = 256

    @cached_property
    def route_table(self):
        """Dense ``(n_nodes, n_nodes, L)`` int32 route table, padded with
        ``n_links`` (a dummy bin), or ``None`` for very large node sets.
        Built once and cached on the model — it depends only on the
        geometry, never on the placement permutation."""
        n = self.n_nodes
        if n > self._VECTOR_TABLE_MAX_NODES:
            return None
        routes = [[self.route(a, b) for b in range(n)] for a in range(n)]
        width = max((len(r) for row in routes for r in row), default=0)
        tab = np.full((n, n, max(width, 1)), self.n_links, np.int32)
        for a in range(n):
            for b in range(n):
                r = routes[a][b]
                if len(r):
                    tab[a, b, :len(r)] = r
        return tab


@dataclass(frozen=True)
class MeasuredModel(TorusModel):
    """A :class:`TorusModel` whose prices come from *measurement* instead of
    the static ``dcn_link_cost`` constant (the self-tuning control plane,
    ``utils/tuner.py``).

    Two measured layers ride on the inherited geometry:

    ``dcn_link_cost``  — replaced by the measured DCN/ICI relative cost, so
                         every inherited consumer (``link_weights``,
                         ``distance``, the route/evaluator stack,
                         ``optimize_placement``, ``synthesize_schedule``)
                         re-prices automatically through inheritance.
    ``edge_cost``      — sorted ``(src_rank, dst_rank, relative_cost)``
                         tuples per directed *transport* edge.  Rank ids,
                         pre-permutation: the link observatory measures
                         between ranks, not chips, and
                         :func:`predicted_edge_cost` consults this map
                         before falling back to routed distance — closing
                         the divergence loop (once the measured model is
                         active, ``bf_link_divergence_ratio`` prices
                         measurement against measurement and settles).

    ``sketch`` is a content hash of the canonical measured inputs and the
    model's ``name`` is ``measured:<sketch>`` — the placement-search and
    synthesis caches key on ``name``, so re-priced artifacts are cached
    (and attributed in provenance) per measured matrix, never blended with
    the static model's entries.  Built only via :meth:`from_measurements`,
    which sorts and quantizes, so two SPMD ranks fed the same merged
    matrix construct byte-identical models (:meth:`canonical_bytes`)."""
    edge_cost: Tuple[Tuple[int, int, float], ...] = ()
    sketch: str = ""

    @cached_property
    def edge_cost_map(self) -> Dict[Tuple[int, int], float]:
        return {(int(s), int(d)): float(c) for s, d, c in self.edge_cost}

    @staticmethod
    def from_measurements(base: TorusModel,
                          edge_cost: Sequence[Tuple[int, int, float]],
                          dcn_link_cost: Optional[float] = None
                          ) -> "MeasuredModel":
        """Derive a measured model from ``base``'s geometry plus measured
        relative edge costs.  Costs are quantized to 6 decimals and edges
        sorted — the canonical form the sketch hashes, making the result
        independent of measurement arrival order."""
        edges = tuple(sorted((int(s), int(d), round(float(c), 6))
                             for s, d, c in edge_cost))
        dcn = float(base.dcn_link_cost if dcn_link_cost is None
                    else round(float(dcn_link_cost), 6))
        # Geometry + measured prices only — deliberately NOT base.name, so
        # re-measuring from an already-measured model with the same matrix
        # reproduces the same sketch (idempotent re-price).
        canon = "|".join(
            [repr(base.dims), repr(base.device_node),
             str(base.n_slices), dcn.hex(), repr(base.wrap)]
            + [f"{s}>{d}={c.hex()}" for s, d, c in edges])
        sketch = hashlib.sha256(canon.encode()).hexdigest()[:12]
        return MeasuredModel(
            name=f"measured:{sketch}", dims=base.dims,
            device_node=base.device_node, n_slices=base.n_slices,
            dcn_link_cost=dcn, wrap=base.wrap,
            edge_cost=edges, sketch=sketch)

    def canonical_bytes(self) -> bytes:
        """Byte-exact serialization (floats as ``float.hex()``, edges in
        sorted order by construction) — what cross-rank determinism tests
        compare to prove two ranks derived the identical model."""
        parts = [self.name, repr(self.dims), repr(self.device_node),
                 str(self.n_slices), float(self.dcn_link_cost).hex(),
                 repr(self.wrap)]
        parts += [f"{s}>{d}={float(c).hex()}" for s, d, c in self.edge_cost]
        return "|".join(parts).encode()


def parse_torus_spec(spec: str) -> Tuple[int, ...]:
    """Parse ``BLUEFOG_TPU_FAKE_TORUS`` — ``RxC`` or ``XxYxZ`` extents."""
    try:
        dims = tuple(int(p) for p in spec.lower().split("x"))
    except ValueError:
        dims = ()
    if not (1 <= len(dims) <= 3) or any(d < 1 for d in dims) \
            or int(np.prod(dims)) < 2:
        raise ValueError(
            f"BLUEFOG_TPU_FAKE_TORUS={spec!r} is not a valid torus spec; "
            "expected 'RxC' or 'XxYxZ' with positive extents and >= 2 "
            "nodes total (e.g. 4x8)")
    return dims


def synthetic_torus(dims: Sequence[int], n_devices: Optional[int] = None,
                    name: Optional[str] = None,
                    n_slices: int = 1) -> TorusModel:
    """Synthetic torus with device ``i`` on node ``i`` (row-major;
    slice-contiguous when ``n_slices > 1`` — devices ``0..nodes-1`` fill
    slice 0, the next block slice 1, ... with one shared DCN link per
    ordered slice pair, exactly like the real-coords multi-slice model).

    ``n_devices`` may exceed the node count when several devices share a
    chip (must divide evenly: devices ``i`` maps to node
    ``i // (n_devices/nodes)``)."""
    dims = tuple(int(d) for d in dims)
    n_slices = int(n_slices)
    nodes = int(np.prod(dims)) * max(n_slices, 1)
    n_devices = nodes if n_devices is None else int(n_devices)
    if n_devices % nodes:
        raise ValueError(
            f"{n_devices} devices do not divide evenly over a "
            f"{'x'.join(map(str, dims))} torus ({nodes} nodes)")
    per = n_devices // nodes
    base = "fake-torus-" + "x".join(map(str, dims))
    if n_slices > 1:
        base += f"-{n_slices}slices"
    return TorusModel(
        name=name or base,
        dims=dims,
        device_node=tuple(i // per for i in range(n_devices)),
        n_slices=max(n_slices, 1))


def build_model(devices) -> Optional[TorusModel]:
    """Interconnect model for a device list, or None (flat fallback).

    Resolution order: the ``BLUEFOG_TPU_FAKE_TORUS`` spec (synthetic torus
    over exactly ``len(devices)`` nodes — a mismatch logs a warning and
    disables the model rather than silently mis-modeling), then real
    ``device.coords`` / ``slice_index`` (TPU), else None — CPU/GPU devices
    carry no interconnect geometry, and with no model the placement layer
    is a structural no-op.

    Real-coords builds decide per-dimension wraparound from the
    ``BLUEFOG_TPU_TORUS_WRAP`` policy: ``auto`` (default) enables wrap on
    3-D dimensions that are multiples of 4 (the v4/v5p optical-wraparound
    slice rule) and models 2-D (v2/v3 sub-pod) slices as meshes; ``1`` /
    ``0`` force all-wrap / no-wrap for operators who know their slice.
    Modeling a wrap link that does not exist would let the optimizer
    route traffic over it — worse than under-modeling, because the
    installed placement would be actively wrong on hardware.  The
    synthetic fake torus always wraps (it is, by declaration, a torus).
    """
    from bluefog_tpu.utils import config
    from bluefog_tpu.utils.logging import get_logger
    spec = config.get().fake_torus
    n = len(devices)
    if spec:
        try:
            dims = parse_torus_spec(spec)
            nodes = 1
            for d in dims:
                nodes *= d
            if nodes != n:
                # Exact match only: synthetic_torus CAN share a node
                # among several devices, but for the env spec a divisor
                # count is far more likely a typo (2x2 for 2x4) than an
                # intent — and a silently mis-modeled geometry drives a
                # real device permutation.
                raise ValueError(
                    f"BLUEFOG_TPU_FAKE_TORUS={spec!r} has {nodes} nodes "
                    f"but the mesh has {n} devices")
            return synthetic_torus(dims, n_devices=n)
        except ValueError as e:
            get_logger().warning(
                "ignoring BLUEFOG_TPU_FAKE_TORUS (%s); physical placement "
                "disabled", e)
            return None
    if n < 2:
        return None
    coords = [getattr(d, "coords", None) for d in devices]
    if any(c is None for c in coords):
        return None
    try:
        coords = [tuple(int(x) for x in c) for c in coords]
    except TypeError:
        return None
    ndims = len(coords[0])
    if not (2 <= ndims <= 3) or any(len(c) != ndims for c in coords):
        return None
    slices = [int(getattr(d, "slice_index", 0) or 0) for d in devices]
    slice_ids = sorted(set(slices))
    slice_pos = {s: i for i, s in enumerate(slice_ids)}
    dims = tuple(max(c[d] for c in coords) + 1 for d in range(ndims))
    # Drop trailing singleton dims (v2/v3 expose (x, y, 0)).
    while len(dims) > 2 and dims[-1] == 1:
        dims = dims[:-1]
        coords = [c[:len(dims)] for c in coords]
    nodes = int(np.prod(dims))
    node_of = []
    for c, s in zip(coords, slices):
        flat = 0
        for x, extent in zip(c, dims):
            flat = flat * extent + x
        node_of.append(slice_pos[s] * nodes + flat)
    policy = (config.get().torus_wrap or "auto").lower()
    if policy in ("1", "true", "always"):
        wrap = (True,) * len(dims)
    elif policy in ("0", "false", "never"):
        wrap = (False,) * len(dims)
    else:  # auto
        if len(dims) >= 3:
            wrap = tuple(d >= 4 and d % 4 == 0 for d in dims)
        else:
            wrap = (False,) * len(dims)
    kind = "torus" if all(wrap) else "mesh"
    name = f"tpu-{kind}-" + "x".join(map(str, dims))
    if len(slice_ids) > 1:
        name += f"-{len(slice_ids)}slices"
    return TorusModel(name=name, dims=dims, device_node=tuple(node_of),
                      n_slices=len(slice_ids), wrap=wrap)


# ---------------------------------------------------------------------------
# Cost model
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CostReport:
    """Modeled physical cost of a round sequence at unit payload per edge."""
    max_link_load: float      # max over rounds of the busiest link's load
    hop_bytes: float          # total weighted link crossings
    serial_link_time: float   # sum of per-round bottlenecks (modeled time)
    rounds: int


def schedule_rounds(scheds) -> List[List[Tuple[int, int]]]:
    """Flatten schedules (Static/Dynamic/PairGossip, or a list of them)
    into the per-round (src, dst) edge lists — the contention domains (a
    round's ppermutes fly concurrently; rounds serialize)."""
    if isinstance(scheds, (list, tuple)):
        out: List[List[Tuple[int, int]]] = []
        for s in scheds:
            out.extend(schedule_rounds(s))
        return out
    phases = getattr(scheds, "phases", None)
    if phases is not None:
        return schedule_rounds(list(phases))
    rnd = getattr(scheds, "round", None)
    rounds = scheds.rounds if rnd is None else (rnd,)
    return [list(r.pairs) for r in rounds]


class _Evaluator:
    """Vectorized cost evaluation of one round set under a permutation.

    The annealing loop calls :meth:`cost` thousands of times, so routing
    must not run per edge per call: the model's dense route table (node →
    node → padded link ids, permutation-independent) turns one round's
    evaluation into a single gather + bincount.  Models too large for the
    table fall back to the per-pair route cache."""

    def __init__(self, model: TorusModel, rounds: List[List[Tuple[int, int]]]):
        self.model = model
        self.rounds = [r for r in rounds if r]
        self.lw = model.link_weights
        self.n_links = model.n_links
        self.node = np.asarray(model.device_node, np.int64)
        self._tab = model.route_table
        if self._tab is not None:
            self._srcs = [np.asarray([s for s, _ in r], np.int64)
                          for r in self.rounds]
            self._dsts = [np.asarray([d for _, d in r], np.int64)
                          for r in self.rounds]
        # Lexicographic scalarization for annealing: K exceeds any
        # achievable hop_bytes, so E = mll * K + hop_bytes orders exactly
        # like (mll, hop_bytes).
        total_edges = sum(len(r) for r in rounds)
        max_route_w = (sum(d // 2 if w else d - 1
                           for d, w in zip(model.dims, model.wrap_dims))
                       + model.dcn_link_cost)
        self.K = float(total_edges * max_route_w + 1.0)

    def cost(self, perm: np.ndarray) -> CostReport:
        mll = 0.0
        hop = 0.0
        serial = 0.0
        if self._tab is not None:
            pnode = self.node[perm]
            for srcs, dsts in zip(self._srcs, self._dsts):
                cat = self._tab[pnode[srcs], pnode[dsts]].ravel()
                # minlength/slice drop the padding bin (id == n_links).
                loads = np.bincount(
                    cat, minlength=self.n_links + 1)[:self.n_links] * self.lw
                if not loads.size:
                    continue
                b = float(loads.max())
                if b == 0.0:
                    continue
                mll = max(mll, b)
                serial += b
                hop += float(loads.sum())
            return CostReport(max_link_load=mll, hop_bytes=hop,
                              serial_link_time=serial,
                              rounds=len(self.rounds))
        for pairs in self.rounds:
            ids = [self.model.route(int(self.node[perm[s]]),
                                    int(self.node[perm[d]]))
                   for s, d in pairs]
            cat = np.concatenate(ids) if ids else np.empty(0, np.int64)
            if cat.size == 0:
                continue
            loads = np.bincount(cat, minlength=self.n_links) * self.lw
            b = float(loads.max())
            mll = max(mll, b)
            serial += b
            hop += float(self.lw[cat].sum())
        return CostReport(max_link_load=mll, hop_bytes=hop,
                          serial_link_time=serial, rounds=len(self.rounds))

    def energy(self, perm: np.ndarray) -> float:
        c = self.cost(perm)
        return c.max_link_load * self.K + c.hop_bytes


def schedule_cost(model: TorusModel, scheds,
                  perm: Optional[np.ndarray] = None) -> CostReport:
    """Modeled cost of compiled schedule(s) under a placement (None =
    enumeration order)."""
    rounds = schedule_rounds(scheds)
    ev = _Evaluator(model, rounds)
    n = len(model.device_node)
    if perm is None:
        perm = np.arange(n)
    return ev.cost(np.asarray(perm, np.int64))


# ---------------------------------------------------------------------------
# Placement optimizer
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PlacementResult:
    perm: np.ndarray           # logical rank -> device index
    is_identity: bool
    identity_cost: CostReport
    optimized_cost: CostReport
    improvement_ratio: float   # identity mll / optimized mll (>= 1.0)
    model_name: str


def _affinity(rounds: List[List[Tuple[int, int]]],
              n: int) -> Dict[int, Dict[int, float]]:
    """Undirected rank-affinity weights: how often two ranks exchange."""
    aff: Dict[int, Dict[int, float]] = {i: {} for i in range(n)}
    for pairs in rounds:
        for s, d in pairs:
            if s == d:
                continue
            aff[s][d] = aff[s].get(d, 0.0) + 1.0
            aff[d][s] = aff[d].get(s, 0.0) + 1.0
    return aff


def _greedy_seed(model: TorusModel, rounds, n: int,
                 block: Optional[int] = None) -> np.ndarray:
    """Affinity-greedy construction: place the most-connected rank first,
    then repeatedly place the rank with the heaviest ties to the placed
    set on the free device minimizing weighted routing distance to its
    placed neighbors.  Deterministic (ties break on lowest index).

    ``block``: restrict rank ``r`` to devices ``d`` with ``d // block ==
    r // block`` (machine-locality constraint — see
    :func:`optimize_placement`)."""
    aff = _affinity(rounds, n)
    node = model.device_node
    placed: Dict[int, int] = {}          # rank -> device
    free = list(range(n))

    def candidates(rank: int) -> List[int]:
        if block is None:
            return list(range(len(free)))
        blk = rank // block
        return [i for i, dev in enumerate(free) if dev // block == blk]

    order_key = lambda r: (-sum(aff[r].values()), r)
    first = min(range(n), key=order_key)
    placed[first] = free.pop(candidates(first)[0])
    while len(placed) < n:
        # Next rank: strongest pull toward the placed set.
        best_r, best_pull = None, (-1.0, 0)
        for r in range(n):
            if r in placed:
                continue
            pull = sum(w for q, w in aff[r].items() if q in placed)
            key = (pull, -r)
            if best_r is None or key > best_pull:
                best_r, best_pull = r, key
        nbrs = [(placed[q], w) for q, w in aff[best_r].items() if q in placed]
        cands = candidates(best_r)
        best_i, best_cost = cands[0], math.inf
        for i in cands:
            dev = free[i]
            c = sum(w * model.distance(node[dev], node[pdev])
                    for pdev, w in nbrs)
            if c < best_cost:
                best_i, best_cost = i, c
        placed[best_r] = free.pop(best_i)
    perm = np.empty(n, np.int64)
    for r, dev in placed.items():
        perm[r] = dev
    return perm


def _anneal(ev: _Evaluator, start: np.ndarray, iters: int,
            rng: np.random.Generator,
            block: Optional[int] = None) -> np.ndarray:
    """Pairwise-swap simulated annealing on the rank→device permutation.
    With ``block`` set, swaps stay within one block so the machine-
    locality constraint of the start permutation is preserved."""
    n = len(start)
    if block is not None and block < 2:
        return start.copy()  # singleton blocks: no legal swap exists
    perm = start.copy()
    cur = ev.energy(perm)
    best, best_e = perm.copy(), cur
    t0 = max(cur * 0.02, 1.0)
    tf = max(t0 * 1e-3, 1e-6)
    for it in range(max(iters, 0)):
        t = t0 * (tf / t0) ** (it / max(iters - 1, 1))
        if block is None:
            i, j = rng.choice(n, size=2, replace=False)
        else:
            base = int(rng.integers(n // block)) * block
            i, j = (base + int(x)
                    for x in rng.choice(block, size=2, replace=False))
        perm[i], perm[j] = perm[j], perm[i]
        e = ev.energy(perm)
        if e <= cur or rng.random() < math.exp(min((cur - e) / t, 0.0)):
            cur = e
            if e < best_e:
                best, best_e = perm.copy(), e
        else:
            perm[i], perm[j] = perm[j], perm[i]
    return best


# Slow-path scale guards: above the dense route table's node cutoff every
# annealing step routes each edge in Python, and the greedy seed is
# O(n² · degree) distance calls — unguarded, the default-on search would
# turn init()/set_topology() on a pod-scale slice into minutes of blocking
# time.  Cap total slow-path edge evaluations and the greedy seed's rank
# count (the clamp is logged; operators who want the full search anyway
# can raise BLUEFOG_TPU_PLACEMENT_ITERS, or skip it with PLACEMENT=0).
_SLOW_EVAL_BUDGET = 1_500_000
_GREEDY_MAX_RANKS = 1024


def optimize_placement(model: TorusModel, scheds, n: int, *,
                       iters: int = 1000, seed: int = 0,
                       block: Optional[int] = None) -> PlacementResult:
    """Best logical-rank → device permutation for the given schedule(s).

    Lexicographic objective ``(max_link_load, hop_bytes)`` over the union
    of every phase's rounds.  Candidates: identity, the greedy affinity
    seed, and the annealed refinement of the better of the two; identity
    wins ties, so an already-optimal (shift-structured) placement is
    returned unchanged and NOTHING is ever made worse than enumeration
    order.  Deterministic in ``seed`` — every SPMD process computes the
    identical permutation from the identical schedule.

    ``block``: machine-locality constraint — the search only considers
    permutations with ``perm[r] // block == r // block``, i.e. each rank
    stays on its enumeration-order machine (devices are enumerated
    process-contiguously, and the hierarchical ``(machine, local)`` mesh
    reshapes consecutive blocks).  The rank-axis search is blind to the
    hierarchical schedules, so without the constraint it could scatter a
    "machine's" ranks across hosts and silently turn every LOCAL_AXIS
    collective into DCN traffic.  A block that does not divide ``n``
    disables the search entirely (identity is returned — never guess at
    a constraint we cannot honor).
    """
    if len(model.device_node) != n:
        raise ValueError(
            f"model covers {len(model.device_node)} devices, need {n}")
    if block is not None and (block < 1 or n % block):
        block = 0  # unhonorable constraint: fall through to identity
    rounds = schedule_rounds(scheds)
    ev = _Evaluator(model, rounds)
    identity = np.arange(n, dtype=np.int64)
    id_cost = ev.cost(identity)
    key = lambda c: (c.max_link_load, c.hop_bytes)

    candidates = [(identity, id_cost)]
    if block != 0:
        if ev._tab is None:
            total_edges = max(sum(len(r) for r in rounds), 1)
            capped = max(_SLOW_EVAL_BUDGET // total_edges, 32)
            if capped < iters:
                from bluefog_tpu.utils.logging import get_logger
                get_logger().warning(
                    "placement search on %s (%d nodes, no dense route "
                    "table): annealing capped at %d of %d iterations to "
                    "bound init-time search cost", model.name,
                    model.n_nodes, capped, iters)
                iters = capped
        sa_start = identity
        if n <= _GREEDY_MAX_RANKS:
            greedy = _greedy_seed(model, rounds, n, block)
            g_cost = ev.cost(greedy)
            candidates.append((greedy, g_cost))
            if key(g_cost) < key(id_cost):
                sa_start = greedy
        rng = np.random.default_rng(seed)
        annealed = _anneal(ev, sa_start, iters, rng, block)
        candidates.append((annealed, ev.cost(annealed)))

    best, best_cost = candidates[0]
    for perm, cost in candidates[1:]:
        if key(cost) < key(best_cost):
            best, best_cost = perm, cost
    is_identity = bool((best == identity).all())
    denom = max(best_cost.max_link_load, 1e-12)
    return PlacementResult(
        perm=best, is_identity=is_identity, identity_cost=id_cost,
        optimized_cost=best_cost,
        improvement_ratio=(id_cost.max_link_load / denom
                           if id_cost.max_link_load else 1.0),
        model_name=model.name)


# ---------------------------------------------------------------------------
# Active physical context (set by basics.set_topology, read by wire stats)
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active: Optional[Tuple[TorusModel, Optional[np.ndarray]]] = None
_active_gen = 0
_hops_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def set_active(model: Optional[TorusModel],
               perm: Optional[np.ndarray]) -> None:
    """Install (or clear, model=None) the process-wide physical context the
    modeled wire-cost telemetry reads.  ``basics`` calls this whenever the
    placement is recomputed; the generation counter invalidates per-
    schedule hop caches."""
    global _active, _active_gen
    with _active_lock:
        _active = None if model is None else (model, perm)
        _active_gen += 1


def active() -> Optional[Tuple[TorusModel, Optional[np.ndarray]]]:
    return _active


def predicted_edge_cost(src: int, dst: int) -> float:
    """The active model's predicted RELATIVE cost for the directed edge
    ``src -> dst`` — what the link observatory prices measured one-way
    delay against (``bf_link_divergence_ratio``).  Uniform 1.0 when no
    model is active (CPU gangs, pre-init): divergence then degrades to
    measured-vs-fastest-link, which is exactly the right alert for a
    modelless run.  Clamped to >= 1.0 — a zero-cost edge (same chip)
    must not make the divergence ratio blow up on wire overhead."""
    with _active_lock:
        act = _active
    if act is None:
        return 1.0
    model, perm = act
    if isinstance(model, MeasuredModel):
        # Measured per-rank edge prices take precedence over routed
        # distance (rank ids, pre-permutation — the observatory measures
        # transport edges, not chips).  Unmeasured edges fall through.
        c = model.edge_cost_map.get((int(src), int(dst)))
        if c is not None:
            return max(float(c), 1.0)
    n = len(model.device_node)
    s, d = int(src), int(dst)
    if not (0 <= s < n and 0 <= d < n):
        return 1.0
    if perm is not None:
        s, d = int(perm[s]), int(perm[d])
    cost = model.distance(int(model.device_node[s]),
                          int(model.device_node[d]))
    return max(float(cost), 1.0)


def modeled_schedule_hops(sched) -> Optional[float]:
    """Modeled weighted hop count of ONE call of a compiled schedule under
    the active physical context, or None when no model is active (or the
    schedule's rank count does not match the modeled device set — e.g.
    machine-level hierarchical schedules).  Unit payload per edge; the
    dispatch layer scales by the per-rank row bytes.  Cached per schedule
    object (schedules are frozen; the cache invalidates on generation).

    The (model, perm, generation) context is snapshotted ONCE — dynamic
    phases all price under the same snapshot, so a concurrent
    ``set_active`` (topology swap on another thread) can never blend two
    models into one reading — and the store re-checks the generation, so
    hops priced against the old model are never cached under the new."""
    with _active_lock:
        act = _active
        gen = _active_gen
    if act is None:
        return None
    model, perm = act
    return _modeled_hops(sched, model, perm, gen)


def _modeled_hops(sched, model: TorusModel, perm: Optional[np.ndarray],
                  gen: int) -> Optional[float]:
    n = getattr(sched, "n", None)
    if n != len(model.device_node):
        return None
    with _active_lock:
        try:
            hit = _hops_cache.get(sched)
        except TypeError:
            hit = None  # non-weakrefable stand-in: uncacheable, not fatal
    if hit is not None and hit[0] == gen:
        return hit[1]
    phases = getattr(sched, "phases", None)
    if phases is not None:  # DynamicSchedule: per-call average over phases
        # Recurse so each phase's value lands in (and reuses) the cache —
        # ONE implementation owns the hop computation below.
        per = [_modeled_hops(ph, model, perm, gen) for ph in phases]
        per = [h for h in per if h is not None]
        hops = sum(per) / len(per) if per else None
    else:
        hops = schedule_cost(model, sched, perm).hop_bytes
    if hops is not None:
        # The DynamicSchedule-level average is cached too: dispatch calls
        # this per op, and re-averaging 16 phases per call (lock + weak
        # lookup each) would blow the ~1µs telemetry budget.
        with _active_lock:
            if gen == _active_gen:
                try:
                    _hops_cache[sched] = (gen, hops)
                except TypeError:
                    pass  # unhashable/unweakrefable stand-ins in tests
    return hops
