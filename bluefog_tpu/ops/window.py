"""One-sided window ops: the async gossip family.

TPU has no remote-memory-access over ICI, so the reference's MPI RMA windows
(``mpi_context.h:41-115``, ``mpi_controller.cc:796-1184``) and NCCL passive-
recv service (``nccl_controller.cc:1113-1238``) are re-designed as a host-side
window store: per-rank main buffers plus one staging buffer per in-neighbor
edge, with per-rank mutexes, version counters and the associated-P scalar
vector (push-sum weights, ``mpi_context.cc:136-156``).  Puts/gets/accumulates
run asynchronously on a worker pool (the honest analogue of the reference's
nonblocking RMA + finalizer threads); ``win_update`` synchronizes and performs
the weighted in-place combine exactly like ``DoWinSync`` + ``AvgWithNeighbor``
(``torch/mpi_win_ops.cc:345-428``).

Semantics preserved from the reference (test oracle:
``test/torch_win_ops_test.py``):
  * ``win_put(t, name, dst_weights)`` overwrites dst's buffer-for-me with
    ``w * t``; ``win_accumulate`` adds instead; ``win_get(name, src_weights)``
    pulls ``w * main[src]`` into my buffer-for-src.
  * ``win_update`` combines self memory with in-neighbor buffers (topology
    weights if weighted, else uniform ``1/(indeg+1)``) and writes the result
    back to self memory.  ``win_update_then_collect`` sums with weight 1 and
    zeroes the staging buffers (push-sum collect).
  * mutexes serialize concurrent writers per rank; version counters expose
    per-edge staleness; associated-P mirrors every put/accumulate/update on a
    scalar so push-sum can de-bias.

A process-global store is correct here because the eager API is single-
controller (all ranks live in this process).  Multi-host DCN transport plugs
in behind the same `_WindowStore` interface.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from contextlib import contextmanager
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "win_create", "win_free", "win_put", "win_put_nonblocking",
    "win_get", "win_get_nonblocking", "win_accumulate",
    "win_accumulate_nonblocking", "win_update", "win_update_then_collect",
    "win_wait", "win_poll", "win_mutex", "get_win_version",
    "get_current_created_window_names", "win_associated_p",
    "turn_on_win_ops_with_associated_p", "turn_off_win_ops_with_associated_p",
]


class _Window:
    """State of one named window across all ranks."""

    def __init__(self, name: str, tensor: np.ndarray, in_nbrs: List[List[int]],
                 out_nbrs: List[List[int]], zero_init: bool):
        n = tensor.shape[0]
        self.name = name
        self.n = n
        self.shape = tensor.shape[1:]
        self.dtype = tensor.dtype
        self.in_nbrs = in_nbrs
        self.out_nbrs = out_nbrs
        # main[i]: rank i's exposed memory (win_get source, win_update self term)
        self.main = tensor.copy()
        # staging[(dst, src)]: data src pushed toward dst (or dst pulled from src)
        self.staging: Dict[tuple, np.ndarray] = {}
        # occupied[(dst, src)]: staging slot holds fresh data (puts mark it,
        # win_update consumes; mirrors the reference's sync semantics)
        for dst in range(n):
            for src in in_nbrs[dst]:
                init = np.zeros(self.shape, self.dtype) if zero_init \
                    else self.main[src].copy()
                self.staging[(dst, src)] = init
        self.versions = np.zeros((n, n), dtype=np.int64)
        self.mutexes = [threading.RLock() for _ in range(n)]
        self.lock = threading.RLock()           # store-structure lock
        # associated-P scalars (push-sum weights); self starts at 1.0
        self.p_main = np.ones(n)
        self.p_staging: Dict[tuple, float] = {k: 0.0 for k in self.staging}


class _WindowStore:
    def __init__(self):
        self.windows: Dict[str, _Window] = {}
        self.lock = threading.RLock()
        self.pool = ThreadPoolExecutor(max_workers=4,
                                       thread_name_prefix="bf-win")
        self.handles: Dict[int, Future] = {}
        self.next_handle = 0
        self.associated_p_enabled = False

    def get(self, name: str) -> _Window:
        with self.lock:
            if name not in self.windows:
                raise KeyError(f"window {name!r} does not exist")
            return self.windows[name]

    def submit(self, fn) -> int:
        with self.lock:
            h = self.next_handle
            self.next_handle += 1
            self.handles[h] = self.pool.submit(fn)
            return h


_store = _WindowStore()


def _any_window_exists() -> bool:
    return bool(_store.windows)


def _free_all_windows() -> None:
    with _store.lock:
        for f in _store.handles.values():
            f.cancel()
        _store.handles.clear()
        _store.windows.clear()


def _to_numpy(x) -> np.ndarray:
    return np.asarray(jax.device_get(x))


def _neighbors_from_topology():
    from bluefog_tpu import basics
    topo = basics.load_topology()
    n = basics.size()
    from bluefog_tpu import topology as topology_util
    in_nbrs = [topology_util.in_neighbor_ranks(topo, r) for r in range(n)]
    out_nbrs = [topology_util.out_neighbor_ranks(topo, r) for r in range(n)]
    return n, in_nbrs, out_nbrs


def _resolve_edge_weights(weights, nbrs_of, default: float, *,
                          peer_is_src: bool = False) -> Dict[tuple, float]:
    """Normalize dst/src weight arguments to ``{(rank, peer): w}``.

    ``weights`` may be None (every edge gets ``default``), a full (n, n)
    matrix in the module-wide ``W[src, dst]`` convention, or a dict
    ``{peer: w}`` applied uniformly (the single-controller reading of the
    reference's per-process dicts).  ``peer_is_src`` marks in-neighbor
    callers (win_get / win_update), where ``r`` is the destination, so the
    matrix lookup is ``W[peer, r]`` instead of ``W[r, peer]``.
    """
    out: Dict[tuple, float] = {}
    n = len(nbrs_of)
    if weights is None:
        for r in range(n):
            for peer in nbrs_of[r]:
                out[(r, peer)] = default
    elif isinstance(weights, dict):
        if weights and isinstance(next(iter(weights)), tuple):
            return {k: float(v) for k, v in weights.items()}
        for r in range(n):
            for peer in nbrs_of[r]:
                if peer in weights:
                    out[(r, peer)] = float(weights[peer])
    else:
        w = np.asarray(weights, dtype=float)
        assert w.shape == (n, n), "weight matrix must be (size, size)"
        for r in range(n):
            for peer in nbrs_of[r]:
                out[(r, peer)] = float(w[peer, r] if peer_is_src else w[r, peer])
    return out


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------

def win_create(tensor, name: str, zero_init: bool = False) -> bool:
    """Create a named window from a rank-major tensor ``(size, ...)``.

    Allocates one staging buffer per in-neighbor edge of the *current*
    topology (which is frozen while windows exist, as in the reference)."""
    n, in_nbrs, out_nbrs = _neighbors_from_topology()
    t = _to_numpy(tensor)
    assert t.shape[0] == n, f"rank-major tensor required (leading dim {n})"
    with _store.lock:
        if name in _store.windows:
            return False
        _store.windows[name] = _Window(name, t, in_nbrs, out_nbrs, zero_init)
    return True


def win_free(name: Optional[str] = None) -> bool:
    with _store.lock:
        if name is None:
            _store.windows.clear()
        elif name in _store.windows:
            del _store.windows[name]
        else:
            return False
    return True


def get_current_created_window_names() -> List[str]:
    with _store.lock:
        return sorted(_store.windows)


# ---------------------------------------------------------------------------
# One-sided ops
# ---------------------------------------------------------------------------

def _validate_edges(edges: Dict[tuple, float], nbrs_of: List[List[int]],
                    *, peer_is_src: bool, op: str) -> None:
    """Reject edges absent from the window's topology — a put/get naming a
    non-neighbor is a caller bug (the reference's MPI graph communicator
    errors likewise), not something to drop silently."""
    for (r, peer) in edges:
        if peer not in nbrs_of[r]:
            kind = "in-neighbor" if peer_is_src else "out-neighbor"
            raise ValueError(
                f"{op}: rank {peer} is not an {kind} of rank {r} in the "
                "window's topology")


def _do_put(name: str, tensor: np.ndarray, edges: Dict[tuple, float],
            require_mutex: bool, accumulate: bool, self_weight=None) -> None:
    try:
        win = _store.get(name)
    except KeyError:
        return  # window freed after dispatch; put becomes a no-op
    for (src, dst), w in edges.items():
        payload = tensor[src] * win.dtype.type(w)
        mutex = win.mutexes[dst] if require_mutex else None
        if mutex:
            mutex.acquire()
        try:
            with win.lock:
                if (dst, src) not in win.staging:
                    continue  # window freed concurrently
                if accumulate:
                    win.staging[(dst, src)] += payload
                else:
                    win.staging[(dst, src)] = payload.copy()
                win.versions[dst, src] += 1
                if _store.associated_p_enabled:
                    if accumulate:
                        win.p_staging[(dst, src)] += w * win.p_main[src]
                    else:
                        win.p_staging[(dst, src)] = w * win.p_main[src]
        finally:
            if mutex:
                mutex.release()
    if self_weight is not None:
        # Self-scaling happens AFTER the edge sends so outgoing payloads carry
        # the PRE-scaled associated-P mass (column-stochastic conservation:
        # self_weight + sum of dst weights == 1 must hold on p_old).
        sw = np.asarray(self_weight, dtype=float)
        with win.lock:
            shape = (-1,) + (1,) * len(win.shape)
            win.main[:] = (tensor * sw.reshape(shape)).astype(win.dtype) \
                if sw.ndim else tensor * win.dtype.type(float(sw))
            if _store.associated_p_enabled:
                win.p_main *= sw if sw.ndim else float(sw)


def win_put_nonblocking(tensor, name: str, *, self_weight=None,
                        dst_weights=None, require_mutex: bool = False) -> int:
    """Scaled overwrite of each destination's buffer-for-me (async).

    ``self_weight`` — scalar or per-rank (n,) vector — rescales my exposed
    memory to ``self_weight * tensor`` (applied after the sends dispatch).
    With associated-P enabled, push-sum column-stochastic scaling applies: the
    caller should pass ``dst_weights``/``self_weight`` summing to 1 per source
    (reference ``_DistributedPushSumOptimizer``,
    ``torch/optimizers.py:1026-1178``)."""
    t = _to_numpy(tensor)
    win = _store.get(name)  # raise early on unknown window
    edges = _resolve_edge_weights(dst_weights, win.out_nbrs, 1.0)
    _validate_edges(edges, win.out_nbrs, peer_is_src=False, op="win_put")
    return _store.submit(
        lambda: _do_put(name, t, edges, require_mutex,
                        accumulate=False, self_weight=self_weight))


def win_put(tensor, name: str, *, self_weight: float = None, dst_weights=None,
            require_mutex: bool = False) -> bool:
    win_wait(win_put_nonblocking(tensor, name, self_weight=self_weight,
                                 dst_weights=dst_weights,
                                 require_mutex=require_mutex))
    return True


def win_accumulate_nonblocking(tensor, name: str, *, self_weight=None,
                               dst_weights=None,
                               require_mutex: bool = False) -> int:
    """Scaled add into each destination's buffer-for-me (async).

    ``self_weight`` semantics as in ``win_put_nonblocking`` (scalar or (n,)
    vector, applied after the sends so P mass is conserved)."""
    t = _to_numpy(tensor)
    win = _store.get(name)  # raise early on unknown window
    edges = _resolve_edge_weights(dst_weights, win.out_nbrs, 1.0)
    _validate_edges(edges, win.out_nbrs, peer_is_src=False,
                    op="win_accumulate")
    return _store.submit(
        lambda: _do_put(name, t, edges, require_mutex,
                        accumulate=True, self_weight=self_weight))


def win_accumulate(tensor, name: str, *, self_weight=None,
                   dst_weights=None, require_mutex: bool = False) -> bool:
    win_wait(win_accumulate_nonblocking(
        tensor, name, self_weight=self_weight, dst_weights=dst_weights,
        require_mutex=require_mutex))
    return True


def _do_get(name: str, edges: Dict[tuple, float], require_mutex: bool) -> None:
    try:
        win = _store.get(name)
    except KeyError:
        return  # window freed after dispatch; get becomes a no-op
    for (dst, src), w in edges.items():
        mutex = win.mutexes[src] if require_mutex else None
        if mutex:
            mutex.acquire()
        try:
            with win.lock:
                if (dst, src) not in win.staging:
                    continue
                win.staging[(dst, src)] = win.main[src] * win.dtype.type(w)
                win.versions[dst, src] += 1
                if _store.associated_p_enabled:
                    win.p_staging[(dst, src)] = w * win.p_main[src]
        finally:
            if mutex:
                mutex.release()


def win_get_nonblocking(name: str, *, src_weights=None,
                        require_mutex: bool = False) -> int:
    """Pull ``w * main[src]`` from each in-neighbor into my staging (async)."""
    win = _store.get(name)
    edges = _resolve_edge_weights(src_weights, win.in_nbrs, 1.0,
                                  peer_is_src=True)
    _validate_edges(edges, win.in_nbrs, peer_is_src=True, op="win_get")
    return _store.submit(lambda: _do_get(name, edges, require_mutex))


def win_get(name: str, *, src_weights=None, require_mutex: bool = False) -> bool:
    win_wait(win_get_nonblocking(name, src_weights=src_weights,
                                 require_mutex=require_mutex))
    return True


# ---------------------------------------------------------------------------
# Update (sync + weighted combine)
# ---------------------------------------------------------------------------

def _default_update_weights(win: _Window):
    from bluefog_tpu import basics
    from bluefog_tpu import topology as topology_util
    if basics.is_topo_weighted():
        wmat = topology_util.weight_matrix(basics.load_topology())
        self_w = np.diag(wmat)
        nbr_w = {(dst, src): wmat[src, dst]
                 for dst in range(win.n) for src in win.in_nbrs[dst]}
    else:
        self_w = np.array([1.0 / (len(win.in_nbrs[r]) + 1) for r in range(win.n)])
        nbr_w = {(dst, src): 1.0 / (len(win.in_nbrs[dst]) + 1)
                 for dst in range(win.n) for src in win.in_nbrs[dst]}
    return self_w, nbr_w


def win_update(name: str, *, self_weight=None, neighbor_weights=None,
               reset_weights: bool = False, require_mutex: bool = False):
    """Combine self memory with in-neighbor staging buffers, in place.

    ``out_i = sw_i * main_i + sum_src w[dst=i,src] * staging[i,src]``; writes
    back to self memory and returns the rank-major result as a jax array.
    ``reset_weights`` zeroes the staging buffers afterwards."""
    win = _store.get(name)
    acquired = []
    if require_mutex:
        for m in win.mutexes:
            m.acquire()
            acquired.append(m)
    try:
        with win.lock:
            if (self_weight is None) != (neighbor_weights is None):
                raise ValueError(
                    "self_weight and neighbor_weights have to be presented at "
                    "the same time (matches reference torch/mpi_ops.py:1050)")
            if self_weight is None and neighbor_weights is None:
                self_w, nbr_w = _default_update_weights(win)
            else:
                n = win.n
                self_w = np.full(n, 1.0 if self_weight is None else self_weight)
                nbr_w = _resolve_edge_weights(
                    neighbor_weights, win.in_nbrs, 1.0, peer_is_src=True)
            out = win.main * self_w.reshape((-1,) + (1,) * len(win.shape)) \
                if isinstance(self_w, np.ndarray) \
                else win.main * self_w
            out = np.asarray(out, dtype=win.dtype)
            p_out = win.p_main * (self_w if isinstance(self_w, np.ndarray)
                                  else np.full(win.n, self_w))
            for (dst, src), w in nbr_w.items():
                if (dst, src) in win.staging:
                    out[dst] += win.staging[(dst, src)] * win.dtype.type(w)
                    p_out[dst] += w * win.p_staging[(dst, src)]
            win.main[:] = out
            if _store.associated_p_enabled:
                win.p_main[:] = p_out
            if reset_weights:
                for k in win.staging:
                    win.staging[k][:] = 0
                    win.p_staging[k] = 0.0
            win.versions[:] = 0
            return jnp.asarray(out)
    finally:
        for m in acquired:
            m.release()


def win_update_then_collect(name: str, *, require_mutex: bool = True):
    """Sum self memory with all received contributions and zero the staging
    buffers — the push-sum collect step (``torch/mpi_ops.py:1206-1260``)."""
    win = _store.get(name)
    all_edges = {(dst, src): 1.0
                 for dst in range(win.n) for src in win.in_nbrs[dst]}
    return win_update(name, self_weight=1.0, neighbor_weights=all_edges,
                      reset_weights=True, require_mutex=require_mutex)


# ---------------------------------------------------------------------------
# Handles / mutex / versions / associated-P
# ---------------------------------------------------------------------------

def win_wait(handle: int) -> bool:
    with _store.lock:
        fut = _store.handles.pop(handle, None)
    if fut is None:
        return True
    from bluefog_tpu.utils import stall
    try:
        with stall.watch(f"win_wait(handle={handle})"):
            fut.result()
    except KeyError:
        return False  # window freed while the op was in flight
    return True


def win_poll(handle: int) -> bool:
    with _store.lock:
        fut = _store.handles.get(handle)
    return fut is None or fut.done()


@contextmanager
def win_mutex(name: str, *, for_self: bool = False,
              ranks: Optional[List[int]] = None):
    """Acquire the distributed mutex of the given ranks (default: my
    out-neighbors; ``for_self`` adds my own rank) — reference
    ``mpi_controller.cc:1532-1602`` exposed via ``bf.win_mutex``."""
    from bluefog_tpu import basics
    win = _store.get(name)
    if ranks is None:
        ranks = sorted(set(basics.out_neighbor_ranks(basics.rank())))
        if for_self:
            ranks = sorted(set(ranks + [basics.rank()]))
    locks = [win.mutexes[r] for r in sorted(set(ranks))]
    for l in locks:
        l.acquire()
    try:
        yield
    finally:
        for l in reversed(locks):
            l.release()


def get_win_version(name: str, rank: Optional[int] = None) -> Dict[int, int]:
    """Per-in-neighbor update counts since the last ``win_update``."""
    from bluefog_tpu import basics
    win = _store.get(name)
    r = basics.rank() if rank is None else rank
    with win.lock:
        return {src: int(win.versions[r, src]) for src in win.in_nbrs[r]}


def win_associated_p(name: str, rank: Optional[int] = None) -> float:
    """The push-sum de-bias scalar of a rank (all ranks if rank is None)."""
    win = _store.get(name)
    with win.lock:
        if rank is None:
            return win.p_main.copy()
        return float(win.p_main[rank])


def turn_on_win_ops_with_associated_p() -> None:
    _store.associated_p_enabled = True


def turn_off_win_ops_with_associated_p() -> None:
    _store.associated_p_enabled = False
